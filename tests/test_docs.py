"""Docs stay honest: the metrics catalog covers every series in code."""

import os
import re

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _source_series():
    names = set()
    pkg = os.path.join(REPO, "vodascheduler_tpu")
    for root, _, files in os.walk(pkg):
        for fn in files:
            if fn.endswith(".py"):
                with open(os.path.join(root, fn)) as f:
                    names.update(re.findall(r'"(voda_[a-z_]+)"', f.read()))
    # Module-name prefix for user scripts, not a metric.
    names.discard("voda_user_script_")
    return names


class TestMetricsCatalog:
    def test_every_series_documented(self):
        with open(os.path.join(REPO, "doc",
                               "prometheus-metrics-exposed.md")) as f:
            doc = f.read()
        missing = sorted(s for s in _source_series() if s not in doc)
        assert not missing, f"undocumented series: {missing}"

    def test_every_documented_series_exists(self):
        with open(os.path.join(REPO, "doc",
                               "prometheus-metrics-exposed.md")) as f:
            documented = set(re.findall(r"`(voda_[a-z_]+)", f.read()))
        stale = sorted(documented - _source_series())
        assert not stale, f"documented but gone: {stale}"

    def test_enough_series_for_reference_parity(self):
        # Reference exposes 17 scheduler + 8 allocator + 7 service series
        # across more processes; the consolidated design should still have
        # a substantial catalog.
        assert len(_source_series()) >= 25

    def test_histogram_series_documented_as_histograms(self):
        """The decision-audit PR's bucketed instruments: each must be in
        the catalog AND typed `histogram` on its row (a histogram family
        scrapes as _bucket/_sum/_count — a reader needs the type to query
        it)."""
        with open(os.path.join(REPO, "doc",
                               "prometheus-metrics-exposed.md")) as f:
            doc = f.read()
        for series in ("voda_scheduler_resched_latency_seconds",
                       "voda_scheduler_actuation_seconds",
                       "voda_scheduler_resize_duration_seconds",
                       "voda_scheduler_phase_seconds",
                       "voda_allocator_algorithm_runtime_seconds",
                       "voda_job_step_time_seconds"):
            rows = [ln for ln in doc.splitlines() if series in ln]
            assert rows, f"{series} missing from the catalog"
            assert any("histogram" in row for row in rows), \
                f"{series} row does not declare type histogram"

    def test_resched_latency_phase_split_documented(self):
        """The decide/actuate latency split (performance observatory):
        the catalog row must name both label values — a reader querying
        the old unlabeled series would silently match nothing."""
        with open(os.path.join(REPO, "doc",
                               "prometheus-metrics-exposed.md")) as f:
            doc = f.read()
        row = next(ln for ln in doc.splitlines()
                   if "voda_scheduler_resched_latency_seconds" in ln)
        assert 'phase="decide"' in row and 'phase="actuate"' in row


class TestApisDoc:
    def test_documented_routes_exist_in_rest_layer(self):
        with open(os.path.join(REPO, "doc", "apis.md")) as f:
            doc = f.read()
        with open(os.path.join(REPO, "vodascheduler_tpu", "service",
                               "rest.py")) as f:
            rest = f.read()
        for route in ("/training", "/algorithm", "/ratelimit",
                      "/allocation", "/metrics"):
            assert route in doc and route in rest

    def test_debug_routes_documented(self):
        """The decision-audit debug surface: routes must exist in the
        REST layer and be documented (apis.md + observability.md)."""
        with open(os.path.join(REPO, "doc", "apis.md")) as f:
            doc = f.read()
        with open(os.path.join(REPO, "vodascheduler_tpu", "service",
                               "rest.py")) as f:
            rest = f.read()
        for route in ("/debug/resched", "/debug/trace", "/debug/profile"):
            assert route in doc and route in rest
        assert "explain" in doc  # the CLI verb riding these routes
        assert "voda top" in doc  # the profile surface's CLI verb

    def test_observability_doc_covers_contract(self):
        """doc/observability.md documents the record schema, the reason
        vocabulary, and the retention knobs."""
        with open(os.path.join(REPO, "doc", "observability.md")) as f:
            doc = f.read()
        from vodascheduler_tpu.obs import (
            REASON_CODES,
            STATUS_REASONS,
            TRIGGERS,
        )
        for code in (sorted(REASON_CODES) + sorted(TRIGGERS)
                     + sorted(STATUS_REASONS)):
            assert code in doc, f"reason/trigger {code!r} undocumented"
        for knob in ("VODA_TRACE_DIR", "VODA_TRACE_RING",
                     "VODA_TRACE_MAX_MB"):
            assert knob in doc, f"retention knob {knob} undocumented"
        for kind in ("resched_audit", "span", "http_access",
                     "status_transition", "modelcheck_counterexample",
                     "perf_report", "recovery_report",
                     "takeover_report"):
            assert kind in doc, f"record kind {kind} undocumented"

    def test_performance_observatory_documented(self):
        """The performance observatory contract is pinned both ways:
        every PHASE_NAMES entry is documented in the phase table, no
        documented phase is undeclared, and the baseline/gate workflow
        terms are present."""
        with open(os.path.join(REPO, "doc", "observability.md")) as f:
            doc = f.read()
        from vodascheduler_tpu.obs import PHASE_NAMES
        assert "Performance observatory" in doc
        for name in sorted(PHASE_NAMES):
            assert f"`{name}`" in doc, f"phase {name!r} undocumented"
        # Reverse: the phase table's rows name only declared phases.
        table = re.findall(r"\| `([a-z_]+)` \| (?:decide|actuate) \|", doc)
        assert set(table) == set(PHASE_NAMES), \
            f"phase table out of sync: {sorted(set(table) ^ set(PHASE_NAMES))}"
        for term in ("perf_baseline.json", "make perf-baseline",
                     "make perf-gate", "/debug/profile", "voda top",
                     "PhaseTimer", "decide_scaling"):
            assert term in doc, f"observatory term {term!r} missing"

    def test_ingestion_plane_documented(self):
        """The fleet-scale ingestion plane's contract is pinned both
        ways: apis.md documents the batch route and the 429 semantics,
        observability.md documents the mechanisms, knobs, and metric
        names, and every documented knob exists in config.py."""
        with open(os.path.join(REPO, "doc", "apis.md")) as f:
            apis = f.read()
        for term in ("/training/batch", "429", "Retry-After",
                     "/debug/ingest", "zero residue"):
            assert term in apis, f"apis.md: ingestion term {term!r} missing"
        with open(os.path.join(REPO, "doc", "observability.md")) as f:
            doc = f.read()
        assert "Ingestion plane" in doc
        for term in ("publish_many", "insert_jobs", "batch mode",
                     "snapshot cache", "shed watermark",
                     "passes_to_quiescent", "voda_admission_shed_total",
                     "voda_events_dropped_total", "voda_event_queue_depth",
                     "/debug/ingest"):
            assert term in doc, f"ingestion-plane term {term!r} missing"
        import vodascheduler_tpu.config as cfg
        for knob, attr in (("VODA_EVENT_QUEUE_MAX", "EVENT_QUEUE_MAX"),
                           ("VODA_EVENT_SHED_WATERMARK",
                            "EVENT_SHED_WATERMARK"),
                           ("VODA_ADMISSION_RETRY_AFTER_SECONDS",
                            "ADMISSION_RETRY_AFTER_SECONDS"),
                           ("VODA_METRICS_CACHE_SECONDS",
                            "METRICS_CACHE_SECONDS")):
            assert knob in doc, f"ingestion knob {knob} undocumented"
            assert hasattr(cfg, attr), f"documented knob {knob} gone"

    def test_fleet_decide_documented(self):
        """The fleet control plane's contract is pinned both ways:
        observability.md documents the executor model, lock order,
        router, knobs, the `fleet_route` record and every ROUTE_REASONS
        code (and names no undeclared one); apis.md documents the fleet
        routes and the CLI verb."""
        with open(os.path.join(REPO, "doc", "observability.md")) as f:
            doc = f.read()
        assert "Fleet decide" in doc
        for term in ("FleetCoordinator", "FleetRouter", "fleet_route",
                     "VODA_FLEET_WORKERS", "VODA_FLEET_ROUTER",
                     "fleet-generation token", "fleet_snapshot",
                     "lock_order.json", "fleet._lock", "/debug/fleet",
                     "voda top --fleet", "fleet_pass_speedup"):
            assert term in doc, f"fleet term {term!r} missing"
        from vodascheduler_tpu.obs import ROUTE_REASONS, SPAN_NAMES
        assert "fleet" in SPAN_NAMES
        for code in sorted(ROUTE_REASONS):
            assert f"`{code}`" in doc, f"route reason {code!r} undocumented"
        # Reverse: the route-reason table's rows name only declared codes.
        import re as _re
        table = _re.findall(
            r"\| `([a-z_]+)` \| [^|]*router[^|]*\||"
            r"\| `(explicit_pool|single_pool|best_score|"
            r"affinity_preferred|router_disabled)` \|", doc)
        documented = {x for pair in table for x in pair if x}
        assert documented <= (ROUTE_REASONS | {"route"}), \
            f"undeclared route reasons documented: {documented - ROUTE_REASONS}"
        with open(os.path.join(REPO, "doc", "apis.md")) as f:
            apis = f.read()
        for term in ("/debug/fleet", "voda top --fleet", "fleet_route",
                     "VODA_FLEET_ROUTER"):
            assert term in apis, f"apis.md: fleet term {term!r} missing"
        import vodascheduler_tpu.config as cfg
        assert hasattr(cfg, "FLEET_WORKERS")
        assert hasattr(cfg, "FLEET_ROUTER")

    def test_observability_doc_covers_concurrency_model(self):
        """The concurrent actuation plane's contract is documented: the
        decide/actuate split, the wave vocabulary (matching the
        histogram's label values), the barrier, and the generation
        token."""
        with open(os.path.join(REPO, "doc", "observability.md")) as f:
            doc = f.read()
        assert "Scheduler concurrency model" in doc
        for term in ("Decide under the lock", "Actuate outside the lock",
                     "wave barrier", "release", "claim", "migrate",
                     "generation", "VODA_ACTUATION_WORKERS",
                     "voda_scheduler_actuation_seconds"):
            assert term in doc, f"concurrency-model term {term!r} missing"


class TestPlacementDoc:
    """doc/placement.md is pinned against the live comms model — both
    directions, same pattern as the other contract docs."""

    def _doc(self):
        with open(os.path.join(REPO, "doc", "placement.md")) as f:
            return f.read()

    def test_every_family_profile_documented(self):
        from vodascheduler_tpu.placement.comms import FAMILY_COLLECTIVES
        doc = self._doc()
        for family in FAMILY_COLLECTIVES:
            assert f"`{family}`" in doc, f"family {family!r} undocumented"

    def test_cost_model_contract_documented(self):
        doc = self._doc()
        for term in ("CollectiveProfile", "comms_fraction",
                     "contiguity_cost", "spread", "host_diameter",
                     "link_gbps", "ici_measured.json", "bench_ici_point",
                     "ASSUMED_LINK_GBPS", "weight_for_category",
                     "profile_for_job", "JobSpec.collectives",
                     "comms_seconds_per_step", "sanity_check_families"):
            assert term in doc, f"cost-model term {term!r} missing"

    def test_objective_and_migration_pricing_documented(self):
        doc = self._doc()
        for term in ("VODA_PLACEMENT_COMMS", "VODA_MIGRATION_PAYBACK_SECONDS",
                     "_pick_host", "_bind_hosts", "d / free_slots",
                     "migration_deferred_unpaid", "resize_seconds",
                     "payback", "VODA_PURE_PLACEMENT"):
            assert term in doc, f"objective term {term!r} missing"
        import vodascheduler_tpu.config as cfg
        assert hasattr(cfg, "MIGRATION_PAYBACK_SECONDS")

    def test_proof_and_surfacing_documented(self):
        doc = self._doc()
        for term in ("topology_mix_trace", "placement_comms_ab",
                     "comms_penalty_mean", "detail.placement_comms",
                     "placement_scoring", "voda explain", "voda top",
                     "set_topology", "perf-gate"):
            assert term in doc, f"proof/surfacing term {term!r} missing"

    def test_cross_linked_from_observability(self):
        with open(os.path.join(REPO, "doc", "observability.md")) as f:
            assert "placement.md" in f.read()


class TestFractionalSharingDoc:
    """doc/fractional-sharing.md is pinned two ways: every load-bearing
    symbol it names must exist in code, and the plane's code-side
    vocabulary must be documented in it."""

    def _doc(self):
        with open(os.path.join(REPO, "doc", "fractional-sharing.md")) as f:
            return f.read()

    def test_resource_model_documented(self):
        doc = self._doc()
        for term in ("resource_class", "resolve_resource_class",
                     "chips_per_host", "FeasibleTable", "frac_feasible",
                     "enforce_feasibility", "validate_result",
                     "feasibility_self_check",
                     "enforce_feasibility_reference",
                     "_feasibility_meta_cached"):
            assert term in doc, f"resource-model term {term!r} missing"
        # The documented classes are exactly the code's vocabulary.
        from vodascheduler_tpu.common.job import RESOURCE_CLASSES
        for rc in RESOURCE_CLASSES:
            assert f"`{rc}`" in doc, f"resource class {rc!r} undocumented"

    def test_baseline_and_interference_documented(self):
        doc = self._doc()
        for term in ("VODA_FRACTIONAL_SHARING", "_footprint_fit_pass",
                     "host_footprint", "FAMILY_INTERFERENCE",
                     "interference_fraction", "cotenancy",
                     "interference_weight_for_category",
                     "set_interference_weights", "_pick_host",
                     "interference_penalty_chip_seconds",
                     "interference_penalty_mean", "sanity_check_families"):
            assert term in doc, f"interference term {term!r} missing"

    def test_semantics_and_proof_documented(self):
        doc = self._doc()
        for term in ("hysteresis_bypassed_fractional_fit",
                     "chip_oversubscribed", "overlapping-partition",
                     "fractional_sharing_ab", "detail.fractional_sharing",
                     "topology_mix_trace", "make perf-baseline",
                     "voda explain", "voda top",
                     "voda_scheduler_fractional_jobs",
                     "voda_placement_cotenant_hosts", "50 ms"):
            assert term in doc, f"semantics/proof term {term!r} missing"
        # Reason + invariant registered in their vocabularies.
        from vodascheduler_tpu.obs import REASON_CODES
        assert "hysteresis_bypassed_fractional_fit" in REASON_CODES
        from vodascheduler_tpu.analysis import modelcheck
        assert "chip_oversubscribed" in modelcheck.INVARIANTS

    def test_cross_linked(self):
        with open(os.path.join(REPO, "doc", "observability.md")) as f:
            assert "fractional-sharing.md" in f.read()
        with open(os.path.join(REPO, "doc", "get-started.md")) as f:
            assert "VODA_FRACTIONAL_SHARING" in f.read()


class TestLearnedModelsDoc:
    """doc/learned-models.md is pinned two ways: every load-bearing
    symbol/knob it names exists in code, and the plane's code-side
    vocabulary (trigger, journal kind, record kind, gauge) is
    documented in it."""

    def _doc(self):
        with open(os.path.join(REPO, "doc", "learned-models.md")) as f:
            return f.read()

    def test_observation_model_documented(self):
        doc = self._doc()
        for term in ("spread", "cotenancy", "fit_serial_seconds",
                     "estimate_comms_fraction", "MIN_DELTA",
                     "decayed_weight", "blend", "drift_exceeds_band",
                     "DRIFT_MIN_WEIGHT", "model_version",
                     "job_infos_for", "_refresh_learned_models",
                     "learned_weight", "interference_weight_from_fraction",
                     "LEARNED_FRACTION_WEIGHT_UNIT", "MAX_COMMS_WEIGHT",
                     "_migration_unpaid"):
            assert term in doc, f"learned-models term {term!r} missing"
        # The documented estimation symbols exist.
        from vodascheduler_tpu.metricscollector import learned
        for sym in ("fit_serial_seconds", "estimate_comms_fraction",
                    "estimate_interference_fraction", "blend",
                    "decayed_weight", "drift_exceeds_band"):
            assert hasattr(learned, sym), f"documented symbol {sym} gone"
        from vodascheduler_tpu.placement import comms
        assert hasattr(comms, "learned_weight")
        assert hasattr(comms, "interference_weight_from_fraction")

    def test_vocabulary_documented(self):
        doc = self._doc()
        from vodascheduler_tpu.obs import JOURNAL_KINDS, TRIGGERS
        assert "model_drift_detected" in TRIGGERS
        assert "jmodel" in JOURNAL_KINDS
        for term in ("model_drift_detected", "jmodel", "whatif_report",
                     "voda_job_model_drift_ratio",
                     "voda explain --whatif", "/debug/whatif",
                     "learned_models_ab", "mismatched_prior_trace",
                     "detail.learned_models", "planner_overhead",
                     "make perf-gate"):
            assert term in doc, f"learned-models term {term!r} missing"

    def test_knobs_documented_and_exist(self):
        import vodascheduler_tpu.config as cfg
        doc = self._doc()
        for knob, attr in (
                ("VODA_LEARNED_MODELS", "LEARNED_MODELS"),
                ("VODA_MODEL_DRIFT_BAND", "MODEL_DRIFT_BAND"),
                ("VODA_MODEL_CONFIDENCE_K", "MODEL_CONFIDENCE_K"),
                ("VODA_MODEL_HALF_LIFE_SECONDS",
                 "MODEL_HALF_LIFE_SECONDS")):
            assert knob in doc, f"knob {knob} undocumented"
            assert hasattr(cfg, attr), f"documented knob {knob} gone"

    def test_cross_linked(self):
        with open(os.path.join(REPO, "doc", "observability.md")) as f:
            obs = f.read()
        assert "learned-models.md" in obs
        assert "whatif_report" in obs
        with open(os.path.join(REPO, "doc", "get-started.md")) as f:
            assert "VODA_LEARNED_MODELS" in f.read()
        with open(os.path.join(REPO, "doc", "apis.md")) as f:
            assert "/debug/whatif" in f.read()
        with open(os.path.join(REPO, "doc", "durability.md")) as f:
            assert "jmodel" in f.read()
        with open(os.path.join(REPO, "vodascheduler_tpu", "service",
                               "rest.py")) as f:
            assert "/debug/whatif" in f.read()


class TestDurabilityDoc:
    """doc/durability.md is pinned two ways: every journal record kind
    and recovery reason in the closed vocabularies is documented (and
    nothing undeclared is), and every load-bearing symbol/knob it names
    exists in code."""

    def _doc(self):
        with open(os.path.join(REPO, "doc", "durability.md")) as f:
            return f.read()

    def test_record_catalog_pinned_both_ways(self):
        from vodascheduler_tpu.obs import JOURNAL_KINDS
        doc = self._doc()
        for kind in JOURNAL_KINDS:
            assert f"`{kind}`" in doc, f"journal kind {kind!r} undocumented"
        table = re.findall(r"\| `(j[a-z]+)` \|", doc)
        assert set(table) == set(JOURNAL_KINDS), \
            f"record catalog out of sync: {sorted(set(table) ^ set(JOURNAL_KINDS))}"

    def test_recovery_reasons_pinned_both_ways(self):
        from vodascheduler_tpu.obs import RECOVERY_REASONS
        doc = self._doc()
        for code in RECOVERY_REASONS:
            assert f"`{code}`" in doc, f"recovery reason {code!r} undocumented"
        table = re.findall(r"\| `([a-z_]+)` \| [^|]*→[^|]*\|", doc)
        assert set(table) <= RECOVERY_REASONS, \
            f"undeclared recovery reasons documented: {sorted(set(table) - RECOVERY_REASONS)}"

    def test_contract_terms_documented(self):
        doc = self._doc()
        for term in ("O_APPEND", "crc32", "torn tail", "JournalCorrupt",
                     "Journal.append", "read_state", "recover_scheduler",
                     "FileLease", "FencedOut", "MemoryLease", "epoch",
                     "jsnap", "voda fsck", "/debug/journal",
                     "make journal-fsck", "make modelcheck-crash",
                     "journal-seam", "crash_recovery_divergence",
                     "recovery_unjournaled_grant", "stale_epoch_write",
                     "skip-journal-on-commit", "apply-before-append",
                     "stale-epoch-accepted",
                     "voda_scheduler_journal_bytes",
                     "voda_scheduler_recovery_seconds",
                     "perf_baseline.json", "recovery_pending"):
            assert term in doc, f"durability term {term!r} missing"

    def test_knobs_documented_and_exist(self):
        import vodascheduler_tpu.config as cfg
        doc = self._doc()
        for knob, attr in (("VODA_JOURNAL", "JOURNAL"),
                           ("VODA_JOURNAL_FSYNC", "JOURNAL_FSYNC"),
                           ("VODA_JOURNAL_COMPACT_BYTES",
                            "JOURNAL_COMPACT_BYTES"),
                           ("VODA_LEASE_TTL_SECONDS",
                            "LEASE_TTL_SECONDS"),
                           ("VODA_JOURNAL_RETIRE_RETENTION_SECONDS",
                            "JOURNAL_RETIRE_RETENTION_SECONDS"),
                           ("VODA_RECOVERY_FASTPATH",
                            "RECOVERY_FASTPATH"),
                           ("VODA_STANDBY", "STANDBY"),
                           ("VODA_STANDBY_POLL_SECONDS",
                            "STANDBY_POLL_SECONDS")):
            assert knob in doc, f"knob {knob} undocumented"
            assert hasattr(cfg, attr), f"documented knob {knob} gone"

    def test_cross_linked(self):
        with open(os.path.join(REPO, "doc", "observability.md")) as f:
            obs = f.read()
        assert "durability.md" in obs
        assert "recovery_report" in obs
        with open(os.path.join(REPO, "doc", "get-started.md")) as f:
            assert "VODA_JOURNAL" in f.read()
        with open(os.path.join(REPO, "doc", "apis.md")) as f:
            apis = f.read()
        assert "/debug/journal" in apis and "voda fsck" in apis
        with open(os.path.join(REPO, "vodascheduler_tpu", "service",
                               "rest.py")) as f:
            assert "/debug/journal" in f.read()

    def test_teeth_and_profile_registered(self):
        from vodascheduler_tpu.analysis import modelcheck
        assert "crash" in modelcheck.PROFILES
        for tooth in ("skip-journal-on-commit", "apply-before-append",
                      "stale-epoch-accepted",
                      "stale-standby-serves-decide"):
            assert tooth in modelcheck.DURABILITY_VARIANTS
        for inv in ("crash_recovery_divergence",
                    "recovery_unjournaled_grant", "stale_epoch_write",
                    "standby_prefix_divergence"):
            assert inv in modelcheck.INVARIANTS

    def test_hot_standby_documented(self):
        """The hot-standby plane (doc/durability.md 'Hot standby') is
        pinned two ways: the shipping protocol, applier state machine,
        and takeover budget are documented; the REST/metric/CLI
        surfaces it names exist in code."""
        doc = self._doc()
        for term in ("Hot standby", "JournalTailer", "StandbyApplier",
                     "HotStandby",
                     "FileTailSource", "HttpTailSource", "resync",
                     "resume_hint", "recovered_state", "Journal.batch",
                     "takeover_report", "probe_fence",
                     "/debug/standby", "/journal/segment",
                     "/journal/snapshot",
                     "voda_scheduler_takeover_seconds",
                     "voda_standby_apply_lag_records",
                     "standby_prefix_divergence",
                     "stale-standby-serves-decide",
                     "make failover-bench", "read_states_parallel",
                     "VODA_RECOVERY_FASTPATH", "failover"):
            assert term in doc, f"hot-standby term {term!r} missing"
        with open(os.path.join(REPO, "vodascheduler_tpu", "service",
                               "rest.py")) as f:
            rest = f.read()
        for route in ("/debug/standby", "/journal/segment",
                      "/journal/snapshot"):
            assert route in rest, f"documented route {route} missing"
        with open(os.path.join(REPO, "doc", "apis.md")) as f:
            apis = f.read()
        for route in ("/debug/standby", "/journal/segment",
                      "/journal/snapshot"):
            assert route in apis, f"route {route} not in apis.md"
        with open(os.path.join(REPO, "doc",
                               "prometheus-metrics-exposed.md")) as f:
            prom = f.read()
        for series in ("voda_scheduler_takeover_seconds",
                       "voda_standby_apply_lag_records"):
            assert series in prom, f"series {series} undocumented"
        from vodascheduler_tpu.durability import (  # noqa: F401
            FileTailSource as _f,
            HotStandby as _h,
            HttpTailSource as _t,
            JournalTailer as _j,
            PoolStandby as _p,
            StandbyApplier as _a,
        )


def _modelcheck_invariants():
    from vodascheduler_tpu.analysis import modelcheck
    return modelcheck.INVARIANTS


class TestLifecycleDoc:
    """doc/design/lifecycle.md is pinned against the live transition
    table — both directions, same pattern as vodalint.RULES."""

    def _doc(self):
        with open(os.path.join(REPO, "doc", "design",
                               "lifecycle.md")) as f:
            return f.read()

    def test_every_declared_edge_documented(self):
        from vodascheduler_tpu.common.lifecycle import TRANSITIONS
        doc = self._doc()
        for (frm, to), spec in TRANSITIONS.items():
            edge = f"`{frm.value} -> {to.value}`"
            assert edge in doc, f"edge {edge} undocumented"
            row = next(ln for ln in doc.splitlines() if edge in ln)
            for reason in spec.reasons:
                assert f"`{reason}`" in row, \
                    f"{edge}: reason {reason!r} missing from its row"

    def test_no_documented_edge_is_undeclared(self):
        from vodascheduler_tpu.common.lifecycle import TRANSITIONS
        from vodascheduler_tpu.common.types import JobStatus
        doc = self._doc()
        documented = set(re.findall(r"`(\w+) -> (\w+)`", doc))
        assert documented, "no edges found in lifecycle.md"
        live = {(f.value, t.value) for (f, t) in TRANSITIONS}
        stale = documented - live
        assert not stale, f"documented but undeclared edges: {stale}"
        for frm, to in documented:
            JobStatus(frm), JobStatus(to)  # raises on a typo'd status

    def test_contracts_documented(self):
        doc = self._doc()
        for term in ("TRANSITIONS", "transition(", "BookingLedger",
                     "commit_pass", "release", "InvalidTransition",
                     "status_transition", "STATUS_REASONS",
                     "recovery_pending", "self-loop"):
            assert term in doc, f"lifecycle contract term {term!r} missing"


class TestStaticAnalysisDoc:
    def test_rule_catalog_matches_linter_registry(self):
        """doc/static-analysis.md documents every vodalint, vodacheck
        AND vodarace rule id, and names no rule no tool has."""
        with open(os.path.join(REPO, "doc", "static-analysis.md")) as f:
            doc = f.read()
        from vodascheduler_tpu.analysis import vodacheck, vodalint, vodarace
        for rule in vodalint.RULES:
            assert f"`{rule}`" in doc, f"vodalint rule {rule!r} undocumented"
        for rule in vodacheck.RULES:
            assert f"`{rule}`" in doc, f"vodacheck rule {rule!r} undocumented"
        for rule in vodarace.RULES:
            assert f"`{rule}`" in doc, f"vodarace rule {rule!r} undocumented"
        documented = set(re.findall(r"\| `([a-z\-_]+)` \|", doc))
        known = (set(vodalint.RULES) | set(vodacheck.RULES)
                 | set(vodarace.RULES) | set(_modelcheck_invariants()))
        unknown = documented - known
        assert not unknown, f"documented but not in any registry: {unknown}"

    def test_modelcheck_invariants_documented(self):
        """The invariant catalog is pinned like the rule catalogs:
        every modelcheck.INVARIANTS id appears in static-analysis.md."""
        with open(os.path.join(REPO, "doc", "static-analysis.md")) as f:
            doc = f.read()
        for inv in _modelcheck_invariants():
            assert f"`{inv}`" in doc, f"invariant {inv!r} undocumented"
        for target in ("make vodacheck", "make modelcheck",
                       "modelcheck-selftest", "replay_counterexample",
                       "2,000"):
            assert target in doc, f"{target!r} missing"

    def test_suppression_syntax_and_artifacts_documented(self):
        with open(os.path.join(REPO, "doc", "static-analysis.md")) as f:
            doc = f.read()
        assert "vodalint: ignore[" in doc
        assert "vodalint_baseline.jsonl" in doc
        assert "lock_order.json" in doc
        assert "make lint" in doc and "make lock-order" in doc
        assert "thread_roles.json" in doc
        for target in ("make racecheck", "racecheck-selftest",
                       "make thread-roles", "--format sarif"):
            assert target in doc, f"{target!r} missing"

    def test_span_vocabulary_documented(self):
        """SPAN_NAMES joins REASON_CODES/TRIGGERS in the pinned-doc
        contract: every span name the code may emit is documented."""
        with open(os.path.join(REPO, "doc", "observability.md")) as f:
            doc = f.read()
        from vodascheduler_tpu.obs import SPAN_NAMES
        for name in sorted(SPAN_NAMES):
            assert f"`{name}`" in doc, f"span name {name!r} undocumented"

    def test_observability_cross_links_static_analysis(self):
        with open(os.path.join(REPO, "doc", "observability.md")) as f:
            assert "static-analysis.md" in f.read()

    def test_lock_order_artifact_pinned(self):
        """doc/lock_order.json is committed, schema-valid, and acyclic
        (a cyclic pinned graph would bless a deadlock)."""
        import json

        from vodascheduler_tpu.analysis.lockwitness import assert_acyclic
        with open(os.path.join(REPO, "doc", "lock_order.json")) as f:
            graph = json.load(f)
        assert graph["schema"] == 1
        assert set(graph) == {"schema", "nodes", "edges"}
        assert graph["edges"]
        assert_acyclic(graph)
        for src, dsts in graph["edges"].items():
            assert src in graph["nodes"]
            assert all(d in graph["nodes"] for d in dsts)

    def test_thread_roles_artifact_pinned(self):
        """doc/thread_roles.json is committed, schema-valid, and embeds
        the SAME prefix→role table the code ships — the witness resolves
        thread names through vodarace.ROLE_PREFIXES, so a drifted copy
        would attribute accesses to the wrong role silently."""
        import json

        from vodascheduler_tpu.analysis import vodarace
        with open(os.path.join(REPO, "doc", "thread_roles.json")) as f:
            pinned = json.load(f)
        assert pinned["schema"] == vodarace.SCHEMA_VERSION
        assert set(pinned) == {"schema", "role_prefixes", "roles",
                               "immutable"}
        assert pinned["role_prefixes"] == dict(vodarace.ROLE_PREFIXES)
        assert pinned["roles"], "ownership map should not be empty"
        assert "main" not in pinned["roles"]
        for role, body in pinned["roles"].items():
            assert role in vodarace.ROLES, f"unknown role {role!r}"
            for cls, attrs in body["access"].items():
                for attr, kinds in attrs.items():
                    assert set(kinds) <= {"read", "write"}, (cls, attr)
                    assert set(kinds.values()) <= {
                        "guarded", "unguarded", "mixed"}, (cls, attr)

    def test_thread_cast_documented(self):
        """observability.md's thread-cast table names every role the
        checker knows (except the excluded 'main')."""
        from vodascheduler_tpu.analysis import vodarace
        with open(os.path.join(REPO, "doc", "observability.md")) as f:
            doc = f.read()
        assert "The thread cast" in doc
        for role in vodarace.ROLES:
            if role == "main":
                continue
            assert f"| {role} |" in doc, f"role {role!r} undocumented"
        for prefix, role in vodarace.ROLE_PREFIXES.items():
            if role == "main":
                continue
            assert f"`{prefix}`" in doc, f"prefix {prefix!r} undocumented"


def test_helm_chart_values_references_resolve():
    """deploy/helm/voda-tpu (reference parity: helm/voda-scheduler):
    Chart/values parse, and every `.Values.<path>` referenced by a
    template exists in values.yaml — the typo class a chart without CI
    rendering would otherwise ship."""
    import glob

    import yaml

    root = os.path.join(REPO, "deploy", "helm", "voda-tpu")
    chart = yaml.safe_load(open(os.path.join(root, "Chart.yaml")))
    assert chart["name"] == "voda-tpu" and chart["version"]
    values = yaml.safe_load(open(os.path.join(root, "values.yaml")))

    def resolve(path):
        node = values
        for key in path.split("."):
            if isinstance(node, list):
                node = node[0]
            if not isinstance(node, dict) or key not in node:
                return False
            node = node[key]
        return True

    templates = glob.glob(os.path.join(root, "templates", "*.yaml"))
    assert len(templates) >= 4
    refs = set()
    for t in templates:
        src = open(t).read()
        refs |= set(re.findall(r"\.Values\.([A-Za-z0-9_.]+)", src))
        # Range-scoped pool fields resolve against the pools entry shape.
        # Pattern tolerates any spacing/casing ({{.name}}, {{ .maxChips }});
        # `$.Values` refs are excluded by the missing-$ lookbehind context.
        for field in re.findall(r"{{-?\s*\.([A-Za-z0-9_]+)\s*-?}}", src):
            assert field in values["pools"][0], field
    assert refs, "no .Values references found"
    for ref in sorted(refs):
        assert resolve(ref), f".Values.{ref} missing from values.yaml"
