"""Clock discipline on the real backends (vodalint's clock-discipline
rule, satellite of the invariant-enforcement plane): every cluster
backend stamps its events with the INJECTED Clock, so a harness driving
one under a VirtualClock gets virtual-time-stamped events — the
replay-determinism property raw time.time() stamps silently broke.

Hermetic: no subprocesses — LocalBackend gets a stub Popen, GkeBackend a
FakeKube, MultiHostBackend pure host churn."""

from vodascheduler_tpu.cluster.backend import ClusterEventKind
from vodascheduler_tpu.cluster.gke import GkeBackend
from vodascheduler_tpu.cluster.local import LocalBackend, _Proc
from vodascheduler_tpu.cluster.multihost import MultiHostBackend
from vodascheduler_tpu.common.clock import Clock, VirtualClock
from vodascheduler_tpu.common.job import JobSpec
from vodascheduler_tpu.common.types import PREEMPTED_EXIT_CODE

from tests.test_gke_backend import FakeKube, make_node, template

T0 = 1_234_500.0


class _ExitedPopen:
    """A process that already exited with the given code."""

    def __init__(self, code: int = 0):
        self._code = code
        self.pid = 4242

    def poll(self):
        return self._code

    def wait(self, timeout=None):
        return self._code

    def send_signal(self, sig):
        pass

    def kill(self):
        pass


def test_local_backend_stamps_events_with_virtual_clock(tmp_path):
    clock = VirtualClock(start=T0)
    backend = LocalBackend(str(tmp_path), chips=8, clock=clock,
                           poll_interval_seconds=0.01)
    events = []
    backend.set_event_callback(events.append)
    backend._specs["job-ok"] = JobSpec(name="job-ok")
    backend._procs["job-ok"] = _Proc(_ExitedPopen(0), 2, 8)
    clock.advance(30.0)
    backend._monitor_loop()  # reaps the exited proc, then idle-exits
    assert [e.kind for e in events] == [ClusterEventKind.JOB_COMPLETED]
    assert events[0].timestamp == T0 + 30.0

    events.clear()
    backend._specs["job-bad"] = JobSpec(name="job-bad")
    backend._procs["job-bad"] = _Proc(_ExitedPopen(PREEMPTED_EXIT_CODE),
                                      2, 8)
    clock.advance(15.0)
    backend._monitor_loop()
    assert [e.kind for e in events] == [ClusterEventKind.JOB_FAILED]
    assert events[0].timestamp == T0 + 45.0
    backend.close()


def test_multihost_backend_stamps_host_events_with_virtual_clock(tmp_path):
    clock = VirtualClock(start=T0)
    backend = MultiHostBackend(str(tmp_path), hosts={"host-0": 4},
                               clock=clock)
    events = []
    backend.set_event_callback(events.append)
    backend.add_host("host-1", 4)
    clock.advance(60.0)
    backend.remove_host("host-1")
    assert [e.kind for e in events] == [ClusterEventKind.HOST_ADDED,
                                        ClusterEventKind.HOST_REMOVED]
    assert events[0].timestamp == T0
    assert events[1].timestamp == T0 + 60.0
    backend.close()


def test_gke_backend_stamps_all_events_with_virtual_clock():
    clock = VirtualClock(start=T0)
    kube = FakeKube([make_node("host-0", chips=8)])
    backend = GkeBackend(kube, pod_template=template(),
                         poll_interval_seconds=600.0, clock=clock)
    events = []
    backend.set_event_callback(events.append)
    backend.start_job(JobSpec(name="job-a"), 4)
    for pod in kube.pods.values():
        pod["status"] = {
            "phase": "Succeeded",
            "containerStatuses": [{"state": {"terminated":
                                             {"exitCode": 0}}}],
        }
    clock.advance(90.0)
    backend.poll_once()
    done = [e for e in events
            if e.kind == ClusterEventKind.JOB_COMPLETED]
    assert len(done) == 1
    assert done[0].timestamp == T0 + 90.0

    # Host churn from the node informer sweep: same virtual stamps.
    events.clear()
    kube.nodes.append(make_node("host-1", chips=8))
    clock.advance(5.0)
    backend.poll_once()
    added = [e for e in events if e.kind == ClusterEventKind.HOST_ADDED]
    assert len(added) == 1 and added[0].timestamp == T0 + 95.0
    backend.close()


def test_backends_default_to_real_clock(tmp_path):
    backend = MultiHostBackend(str(tmp_path))
    assert isinstance(backend.clock, Clock)
    assert not isinstance(backend.clock, VirtualClock)
    backend.close()


def test_app_threads_one_clock_into_its_backends(tmp_path):
    """The composition root must hand ITS clock to every backend it
    builds — a silent per-backend Clock() fallback would re-open the
    wall-clock drift this plane closed."""
    from vodascheduler_tpu.service.app import VodaApp

    app = VodaApp(str(tmp_path), chips=4, hermetic_devices=4)
    try:
        assert app.schedulers
        for sched in app.schedulers.values():
            assert sched.backend.clock is app.clock
    finally:
        app.stop()
