"""Mesh planning, sharding rules, and ring attention tests (8-device
virtual CPU mesh from conftest)."""

import jax
import numpy as np
import jax.numpy as jnp
import pytest

from tests import helpers
from vodascheduler_tpu.parallel.mesh import MeshPlan, build_mesh, plan_mesh
from vodascheduler_tpu.parallel.ring_attention import (
    make_ring_attention,
    reference_attention,
)
from vodascheduler_tpu.parallel.sharding import (
    TRANSFORMER_RULES,
    _fit_spec,
    batch_sharding,
)
from jax.sharding import PartitionSpec as P


class TestMeshPlan:
    def test_small_model_pure_dp(self):
        plan = plan_mesh(8, model_params_b=0.1)
        assert plan.dp == 8 and plan.tp == 1 and plan.fsdp == 1

    def test_large_model_gets_tp_and_fsdp(self):
        plan = plan_mesh(8, model_params_b=8.0)
        assert plan.tp > 1 and plan.fsdp > 1
        assert plan.num_chips == 8

    def test_long_context_gets_sp(self):
        plan = plan_mesh(8, model_params_b=8.0, seq_len=65536)
        assert plan.sp > 1
        assert plan.num_chips == 8

    def test_moe_gets_ep(self):
        plan = plan_mesh(8, num_experts=8)
        assert plan.ep > 1
        assert plan.num_chips == 8

    def test_build_mesh_axis_names(self):
        mesh = build_mesh(MeshPlan(dp=2, fsdp=2, tp=2))
        assert mesh.shape["dp"] == 2
        assert mesh.shape["tp"] == 2

    def test_build_mesh_too_few_devices(self):
        with pytest.raises(ValueError):
            build_mesh(MeshPlan(dp=16))


class TestShardingRules:
    def test_transformer_rule_matching(self):
        assert TRANSFORMER_RULES.spec_for("layer_0/attn/q_proj/kernel") == P("fsdp", "tp")
        assert TRANSFORMER_RULES.spec_for("layer_3/mlp/down_proj/kernel") == P("tp", "fsdp")
        assert TRANSFORMER_RULES.spec_for("layer_1/attn_norm/scale") == P()
        assert TRANSFORMER_RULES.spec_for("embed/embedding") == P("fsdp", "tp")

    def test_fit_spec_drops_nondividing_axes(self):
        mesh = build_mesh(MeshPlan(dp=2, fsdp=2, tp=2))
        # dim 3 not divisible by fsdp=2 -> replicated on that dim
        assert _fit_spec(P("fsdp", "tp"), (3, 4), mesh) == P(None, "tp")
        assert _fit_spec(P("fsdp", "tp"), (4, 4), mesh) == P("fsdp", "tp")
        # spec longer than rank is trimmed
        assert _fit_spec(P("fsdp", "tp"), (8,), mesh) == P("fsdp")

    def test_batch_sharding_uses_data_axes(self):
        mesh = build_mesh(MeshPlan(dp=2, fsdp=2, tp=2))
        spec = batch_sharding(mesh).spec
        assert spec == P(("dp", "fsdp"))


@pytest.mark.skipif(not helpers.JAX_HAS_ABSTRACT_MESH,
                    reason=helpers.NEEDS_ABSTRACT_MESH)
class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, causal):
        mesh = build_mesh(MeshPlan(dp=2, sp=4))
        B, S, H, D = 2, 32, 4, 8
        q, k, v = (jax.random.normal(kk, (B, S, H, D), dtype=jnp.float32)
                   for kk in jax.random.split(jax.random.PRNGKey(0), 3))
        ring = make_ring_attention(mesh, causal=causal)
        out = jax.jit(ring)(q, k, v)
        ref = reference_attention(q, k, v, causal=causal)
        assert float(jnp.max(jnp.abs(out - ref))) < 1e-5

    @pytest.mark.parametrize("causal", [True, False])
    def test_grads_match_reference(self, causal):
        """Backward through the checkpointed ring loop (each block step
        rematerializes its p matrix) must match the dense reference."""
        mesh = build_mesh(MeshPlan(dp=2, sp=4))
        B, S, H, D = 2, 32, 4, 8
        q, k, v = (jax.random.normal(kk, (B, S, H, D), dtype=jnp.float32)
                   for kk in jax.random.split(jax.random.PRNGKey(2), 3))
        w = jax.random.normal(jax.random.PRNGKey(3), q.shape)
        ring = make_ring_attention(mesh, causal=causal)

        g_ring = jax.grad(lambda *a: jnp.sum(ring(*a) * w),
                          argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(
            lambda *a: jnp.sum(reference_attention(*a, causal=causal) * w),
            argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g_ring, g_ref, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-5, rtol=5e-5,
                                       err_msg=f"d{name} mismatch")

    def test_degenerate_single_shard(self):
        mesh = build_mesh(MeshPlan(dp=8))
        B, S, H, D = 1, 16, 2, 8
        q, k, v = (jax.random.normal(kk, (B, S, H, D), dtype=jnp.float32)
                   for kk in jax.random.split(jax.random.PRNGKey(1), 3))
        out = make_ring_attention(mesh, causal=True)(q, k, v)
        ref = reference_attention(q, k, v, causal=True)
        assert float(jnp.max(jnp.abs(out - ref))) < 1e-5


class TestNoInvoluntaryResharding:
    @pytest.mark.slow
    def test_dp_fsdp_tp_step_has_no_involuntary_remat(self):
        """GSPMD must not fall back to full rematerialization anywhere in
        the train step (regression: the embed table's old P(tp, fsdp)
        sharding leaked feature sharding into the gather output).

        Runs a positive control first — the old bad rule must reproduce
        the warning — so the assertion can't pass vacuously if a jaxlib
        upgrade rewords or reroutes the log."""
        import subprocess
        import sys

        def run_step(patch_bad_rule: bool) -> str:
            patch = (
                "import vodascheduler_tpu.parallel.sharding as sh\n"
                "from jax.sharding import PartitionSpec as P\n"
                "sh.TRANSFORMER_RULES.rules[0] = "
                "(r'embed.*embedding$', P('tp', 'fsdp'))\n"
                "sh.constrain_batch_activation = lambda x: x\n"
                # The fused chunked-CE loss restructures the graph enough
                # that the known-bad rule no longer trips the warning;
                # the control reproduces it on the plain-logits loss.
                "import vodascheduler_tpu.models.registry as reg\n"
                "reg_loss_override = reg._lm_loss\n"
            ) if patch_bad_rule else "reg_loss_override = None\n"
            code = (
                "import jax; jax.config.update('jax_platforms','cpu')\n"
                + patch +
                "from vodascheduler_tpu.models import get_model\n"
                "from vodascheduler_tpu.parallel.mesh import MeshPlan\n"
                "from vodascheduler_tpu.runtime import TrainSession\n"
                "bundle = get_model('llama_tiny')\n"
                "if reg_loss_override is not None:\n"
                "    bundle.loss_fn = reg_loss_override\n"
                "s = TrainSession(bundle, num_chips=8,\n"
                "                 global_batch_size=4,\n"
                "                 plan=MeshPlan(dp=2, fsdp=2, tp=2),\n"
                "                 devices=jax.devices()[:8])\n"
                "s.run_steps(1)\n"
            )
            proc = subprocess.run([sys.executable, "-c", code],
                                  capture_output=True, text=True,
                                  timeout=420)
            assert proc.returncode == 0, proc.stderr[-2000:]
            return proc.stderr

        marker = "Involuntary full rematerialization"
        control = run_step(patch_bad_rule=True)
        assert marker in control, (
            "positive control failed: the known-bad sharding no longer "
            "reproduces the GSPMD warning — update this test's marker")
        assert marker not in run_step(patch_bad_rule=False)
