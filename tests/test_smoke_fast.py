"""Fast JAX-path smoke: one compact slice of each compile-heavy module.

The full matrices live in test_models / test_checkpoint / test_ops, which
are `slow` (CPU-mesh GSPMD compiles dominate on a single core; `make
test-all` runs everything). This file keeps `make test` honest about the
training core: if any of these break, the slow suite is broken too.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vodascheduler_tpu.models import get_model
from vodascheduler_tpu.parallel.mesh import MeshPlan
from vodascheduler_tpu.runtime import TrainSession, latest_step

from tests import helpers


@pytest.mark.skipif(not helpers.JAX_HAS_ABSTRACT_MESH,
                    reason=helpers.NEEDS_ABSTRACT_MESH)
def test_llama_tiny_trains_and_reshards(tmp_path):
    """Train on dp2, checkpoint, restore on a 4-chip fsdp mesh, continue:
    the end-to-end elastic slice (models + sharding + checkpoint) in one
    compile budget."""
    bundle = get_model("llama_tiny")
    s = TrainSession(bundle, 2, devices=jax.devices()[:2],
                     global_batch_size=4, seed=3)
    first = s.run_steps(2)
    assert np.isfinite(first)
    ckpt = tmp_path / "ckpt"
    s.save(str(ckpt))
    s.finish_saves()
    assert latest_step(str(ckpt)) == 2

    r = TrainSession.resume(bundle, 4, str(ckpt),
                            devices=jax.devices()[:4],
                            global_batch_size=4,
                            plan=MeshPlan(dp=2, fsdp=2))
    assert r.step == 2
    # Restored params match bit-exactly across the mesh change.
    for a, b in zip(jax.tree.leaves(s.state["params"]),
                    jax.tree.leaves(r.state["params"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert np.isfinite(r.run_steps(1))


@pytest.mark.skipif(not helpers.JAX_HAS_PALLAS_COMPILER_PARAMS,
                    reason=helpers.NEEDS_PALLAS_COMPILER_PARAMS)
def test_flash_attention_tiny_parity():
    """One interpreter-mode Pallas point vs the O(S²) reference —
    values and grads (the sweep lives in test_ops)."""
    from vodascheduler_tpu.ops import flash_attention
    from vodascheduler_tpu.parallel.ring_attention import (
        reference_attention,
    )

    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    shape = (1, 128, 2, 32)  # [B, S, H, D]
    q = jax.random.normal(k1, shape, jnp.float32)
    k = jax.random.normal(k2, shape, jnp.float32)
    v = jax.random.normal(k3, shape, jnp.float32)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, causal=True, interpret=True).sum()

    def loss_ref(q, k, v):
        return reference_attention(q, k, v, causal=True).sum()

    gf = jax.grad(loss_flash)(q, k, v)
    gr = jax.grad(loss_ref)(q, k, v)
    np.testing.assert_allclose(np.asarray(loss_flash(q, k, v)),
                               np.asarray(loss_ref(q, k, v)), rtol=2e-4)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gr), atol=2e-4,
                               rtol=2e-3)


@pytest.mark.skipif(not helpers.JAX_HAS_ABSTRACT_MESH,
                    reason=helpers.NEEDS_ABSTRACT_MESH)
def test_mixtral_tiny_single_step():
    """MoE path stays alive in the fast suite (full matrix in
    test_models)."""
    bundle = get_model("mixtral_tiny")
    s = TrainSession(bundle, 2, devices=jax.devices()[:2],
                     global_batch_size=4)
    assert np.isfinite(s.run_steps(1))
