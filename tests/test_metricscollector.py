"""Metrics-feedback-loop tests: CSV logger, collector math, and the closed
loop (telemetry -> curves -> smarter allocations)."""

import os

from vodascheduler_tpu.allocator import ResourceAllocator
from vodascheduler_tpu.cluster.fake import FakeClusterBackend, MetricsRow, WorkloadProfile
from vodascheduler_tpu.common.clock import VirtualClock
from vodascheduler_tpu.common.events import EventBus
from vodascheduler_tpu.common.job import JobConfig, JobSpec, base_job_info
from vodascheduler_tpu.common.store import JobStore
from vodascheduler_tpu.metricscollector import (
    BackendRowSource,
    CsvDirRowSource,
    EpochCsvLogger,
    MetricsCollector,
)
from vodascheduler_tpu.metricscollector.csv_logger import resume_epoch
from vodascheduler_tpu.scheduler import Scheduler
from vodascheduler_tpu.service import AdmissionService


class TestCsvLogger:
    def test_roundtrip_and_resume(self, tmp_path):
        logger = EpochCsvLogger(str(tmp_path), "job-a", total_epochs=10,
                                global_batch_size=256)
        logger.log_epoch(12.5, 0.125, workers=4)
        logger.log_epoch(11.0, 0.110, workers=4)
        assert resume_epoch(logger.path) == 2
        # restart: a fresh logger resumes the epoch counter (reference:
        # callbacks.py:58-66)
        logger2 = EpochCsvLogger(str(tmp_path), "job-a", total_epochs=10)
        assert logger2.next_epoch == 2
        logger2.log_epoch(6.0, 0.06, workers=8)
        src = CsvDirRowSource(str(tmp_path))
        rows = src.rows("job-a")
        assert [r.epoch for r in rows] == [0, 1, 2]
        assert rows[2].workers == 8
        # The real step_time_sec column round-trips (collector ingests it
        # into info.step_seconds, not a derived alias).
        assert [r.step_time_sec for r in rows] == [0.125, 0.110, 0.06]


class TestCollectorMath:
    def _store_with_job(self, name="j-20260101-000000", epochs=10):
        store = JobStore()
        spec = JobSpec(name=name,
                       config=JobConfig(min_num_chips=1, max_num_chips=8,
                                        epochs=epochs))
        from vodascheduler_tpu.common.job import TrainingJob
        store.insert_job(TrainingJob.from_spec(spec, submit_time=0.0))
        store.upsert_job_info(base_job_info(name, "j", "pool"))
        return store, name

    def _source(self, rows):
        class Src:
            def job_names(self):
                return list({r.job for r in rows})

            def rows(self, job):
                return [r for r in rows if r.job == job]
        return Src()

    def test_speedup_from_measurements(self):
        store, name = self._store_with_job()
        rows = [
            MetricsRow(name, 0, 100.0, 1, 0),
            MetricsRow(name, 1, 100.0, 1, 0),
            MetricsRow(name, 2, 30.0, 4, 0),
            MetricsRow(name, 3, 28.0, 4, 0),
        ]
        collector = MetricsCollector(store, self._source(rows))
        assert collector.collect_all() == 1
        info = store.get_job_info(name)
        assert info.epoch_seconds[1] == 100.0
        assert info.epoch_seconds[4] == 29.0
        assert abs(info.speedup[4] - 100.0 / 29.0) < 1e-9
        assert abs(info.efficiency[4] - 100.0 / 29.0 / 4) < 1e-9
        # remaining: 10 epochs total, newest epoch 3 -> 6 remaining, serial
        assert info.remaining_epochs == 6
        assert abs(info.estimated_remaining_seconds - 600.0) < 1e-9

    def test_elastic_job_without_1chip_measurement(self):
        # Reference crashes here (epoch_time['1'] KeyError); we infer.
        store, name = self._store_with_job()
        rows = [MetricsRow(name, 0, 25.0, 4, 0),
                MetricsRow(name, 1, 25.0, 4, 0)]
        collector = MetricsCollector(store, self._source(rows))
        collector.collect_all()
        info = store.get_job_info(name)
        # prior speedup[4]=4 -> inferred epoch1 = 100
        assert abs(info.speedup[4] - 4.0) < 1e-9
        assert abs(info.estimated_remaining_seconds - 100.0 * 8) < 1e-9

    def test_same_epoch_skipped(self):
        store, name = self._store_with_job()
        rows = [MetricsRow(name, 0, 10.0, 2, 0)]
        collector = MetricsCollector(store, self._source(rows))
        assert collector.collect_all() == 1
        assert collector.collect_all() == 0  # same newest epoch -> skip

    def test_step_times_ingested_and_curves_diverge(self):
        """The CSV's real step_time_sec feeds info.step_seconds (reference
        metrics_collector.py:131-141 ingests both columns). Epoch time
        carries a fixed per-epoch overhead (eval/checkpoint) of 10s here,
        so the epoch curve shows sublinear speedup while the pure step
        curve scales perfectly — the two must diverge."""
        store, name = self._store_with_job()
        # 100 steps/epoch at 1 worker: step 1.0s -> compute 100s + 10s
        # fixed = 110s. At 4 workers: step 0.25s -> 25s + 10s = 35s.
        rows = [
            MetricsRow(name, 0, 110.0, 1, 0, step_time_sec=1.0),
            MetricsRow(name, 1, 35.0, 4, 0, step_time_sec=0.25),
        ]
        collector = MetricsCollector(store, self._source(rows))
        assert collector.collect_all() == 1
        info = store.get_job_info(name)
        assert info.step_seconds[1] == 1.0
        assert info.step_seconds[4] == 0.25
        assert info.epoch_seconds[4] == 35.0
        step_speedup = info.step_seconds[1] / info.step_seconds[4]
        epoch_speedup = info.epoch_seconds[1] / info.epoch_seconds[4]
        assert abs(step_speedup - 4.0) < 1e-9
        assert abs(epoch_speedup - 110.0 / 35.0) < 1e-9
        assert step_speedup > epoch_speedup + 0.5  # genuinely diverged

    def test_step_times_fall_back_to_epoch_when_unreported(self):
        """Rows without a step measurement (step_time_sec 0.0 — e.g. the
        fake backend's simulated telemetry) keep the derived behavior."""
        store, name = self._store_with_job()
        rows = [MetricsRow(name, 0, 40.0, 2, 0)]
        collector = MetricsCollector(store, self._source(rows))
        collector.collect_all()
        info = store.get_job_info(name)
        assert info.step_seconds[2] == info.epoch_seconds[2] == 40.0

    def test_mixed_reported_and_unreported_step_rows(self):
        """A count with SOME step measurements averages only those; a
        count with none falls back — per-count, not all-or-nothing."""
        store, name = self._store_with_job()
        rows = [
            MetricsRow(name, 0, 50.0, 2, 0, step_time_sec=0.5),
            MetricsRow(name, 1, 54.0, 2, 0),             # sensor gap
            MetricsRow(name, 2, 30.0, 4, 0),             # no step source
        ]
        collector = MetricsCollector(store, self._source(rows))
        collector.collect_all()
        info = store.get_job_info(name)
        assert info.step_seconds[2] == 0.5     # mean of reported only
        assert info.epoch_seconds[2] == 52.0
        assert info.step_seconds[4] == 30.0    # fallback to epoch


class TestClosedLoop:
    def test_curves_learned_in_simulation_inform_srjf(self):
        """Run two jobs under the collector; after telemetry accrues, the
        learned remaining-time estimates should order SRJF correctly."""
        clock = VirtualClock(start=1753760000.0)
        store, bus = JobStore(), EventBus()
        backend = FakeClusterBackend(clock, restart_overhead_seconds=2.0)
        for i in range(2):
            backend.add_host(f"h{i}", 4, announce=False)
        backend.register_profile("fast", WorkloadProfile(epoch_seconds_at_1=20.0))
        backend.register_profile("slow", WorkloadProfile(epoch_seconds_at_1=200.0))
        sched = Scheduler("pool", backend, store, ResourceAllocator(store),
                          clock, bus=bus, algorithm="ElasticFIFO",
                          rate_limit_seconds=5.0)
        admission = AdmissionService(store, bus, clock)
        collector = MetricsCollector(store, BackendRowSource(backend), clock,
                                     interval_seconds=30.0)
        collector.start()

        fast = admission.create_training_job(JobSpec(
            name="fast", pool="pool",
            config=JobConfig(min_num_chips=1, max_num_chips=4, epochs=500)))
        slow = admission.create_training_job(JobSpec(
            name="slow", pool="pool",
            config=JobConfig(min_num_chips=1, max_num_chips=4, epochs=500)))
        clock.advance(600.0)

        fi = store.get_job_info(fast)
        si = store.get_job_info(slow)
        assert fi.current_epoch > 0
        assert si.current_epoch >= 0
        # fast epochs take ~20s serial, slow ~200s serial
        assert fi.estimated_remaining_seconds < si.estimated_remaining_seconds
        # learned speedup is sublinear (profile exponent 0.9), below prior
        measured = [n for n in fi.speedup if n in fi.epoch_seconds and n > 1]
        for n in measured:
            assert fi.speedup[n] < n + 1e-6


class TestTpuMonitor:
    def test_collects_device_count_and_exposes(self):
        from vodascheduler_tpu.common.metrics import Registry
        from vodascheduler_tpu.runtime.tpu_monitor import TpuMonitor

        registry = Registry()
        mon = TpuMonitor(registry)
        mon.collect_once()
        text = registry.exposition()
        assert "voda_tpu_devices" in text
        # CPU test platform: 8 virtual devices (conftest)
        assert mon.m_devices.value() == 8.0
        mon.collect_once()  # idempotent full rebuild

    def test_stale_device_series_cleared_on_rebuild(self):
        from vodascheduler_tpu.common.metrics import Registry
        from vodascheduler_tpu.runtime.tpu_monitor import TpuMonitor

        registry = Registry()
        mon = TpuMonitor(registry)
        g = mon.m_mem["voda_tpu_memory_bytes_in_use"]
        g.set(999.0, device="99", platform="gone")
        mon.collect_once()
        # a device not observed this poll must not keep exporting
        assert 'device="99"' not in registry.exposition()

    def test_sdk_utilization_metrics_exported(self, monkeypatch):
        """Duty-cycle/tensorcore telemetry (the nvidia_smi_exporter role,
        reference README.md:94) rides the same collect_once sweep; the
        libtpu SDK source is injected since CI owns no chips."""
        from vodascheduler_tpu.common.metrics import Registry
        from vodascheduler_tpu.runtime import tpu_monitor
        from vodascheduler_tpu.runtime.tpu_monitor import TpuMonitor

        registry = Registry()
        mon = TpuMonitor(registry)
        monkeypatch.setattr(tpu_monitor, "_read_sdk_metrics", lambda: {
            "duty_cycle_pct": [87.5],
            "tensorcore_util": [42.0],
            "hbm_capacity_usage": [11.0e9],
        })
        mon.collect_once()
        text = registry.exposition()
        assert 'voda_tpu_duty_cycle_pct{accelerator="0"} 87.5' in text
        assert 'voda_tpu_tensorcore_util_pct{accelerator="0"} 42.0' in text
        assert 'voda_tpu_hbm_usage_bytes{accelerator="0"} 11000000000.0' in text
        # Unreported metrics export no stale series.
        assert 'voda_tpu_throttle_score{' not in text
        # Chips lost (e.g. job took ownership): series clear next sweep.
        monkeypatch.setattr(tpu_monitor, "_read_sdk_metrics", lambda: {})
        mon.collect_once()
        assert 'voda_tpu_duty_cycle_pct{' not in registry.exposition()

    def test_read_sdk_metrics_off_tpu_is_empty_or_partial(self):
        """The real reader degrades to {} (or parseable floats) without
        chips — never raises. On this image libtpu is installed but the
        process owns no accelerator, so data() comes back empty."""
        from vodascheduler_tpu.runtime.tpu_monitor import _read_sdk_metrics

        out = _read_sdk_metrics()
        assert isinstance(out, dict)
        for values in out.values():
            assert all(isinstance(v, float) for v in values)

    def test_labeled_gauge_exposition_format(self):
        from vodascheduler_tpu.common.metrics import Registry

        registry = Registry()
        g = registry.gauge("voda_tpu_memory_bytes_in_use", "test",
                           labels=("device", "platform"))
        g.set(123.0, device="0", platform="tpu")
        g.set(456.0, device="1", platform="tpu")
        text = registry.exposition()
        assert 'voda_tpu_memory_bytes_in_use{device="0",platform="tpu"} 123.0' in text
        assert 'voda_tpu_memory_bytes_in_use{device="1",platform="tpu"} 456.0' in text
        assert g.value(device="1", platform="tpu") == 456.0
