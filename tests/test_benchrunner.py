"""The benchmark orchestration plane (vodascheduler_tpu/benchrunner/):
per-point subprocess isolation, watchdog kills, provenance-tagged cache
back-fill, and crash-safe journal resume. Debug points keep these fast
(no jax in the workers); the real-measurement path on hardware shares
every line of orchestration code.
"""

import json
import os

import pytest

from vodascheduler_tpu.benchrunner import (
    BenchOrchestrator,
    BenchPoint,
    default_registry,
    ordered,
    run_key_for,
    to_hardware_section,
    validate_summary,
)
from vodascheduler_tpu.benchrunner.cache import ResultCache
from vodascheduler_tpu.benchrunner.journal import RunJournal

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def ok_point(pid, data=None, risk=0, section=None):
    return BenchPoint(pid, "debug",
                      {"behavior": "ok", "data": data or {"id": pid}},
                      risk=risk, section=section)


def orch(points, tmp_path, **kw):
    return BenchOrchestrator(
        points, repo_dir=REPO,
        cache_path=os.fspath(tmp_path / "cache.json"),
        journal_path=os.fspath(tmp_path / "journal.jsonl"), **kw)


class TestRegistry:
    def test_risk_ordering_riskiest_last(self):
        pts = default_registry(
            model_points=[("llama_350m", 8), ("llama_1b", 4),
                          ("llama_350m_af", 8)],
            attention_points=[(8, 1024), (1, 8192)],
            moe_batch=8, resize_points=[("llama_350m", 8)])
        ids = [p.point_id for p in pts]
        # meta probes first; the known-good flagship before the risky
        # compiles; resize last (its children must own the chip).
        assert ids[0] == "meta"
        assert ids.index("model:llama_350m:b8") < ids.index(
            "model:llama_350m_af:b8")
        assert ids.index("model:llama_350m_af:b8") < ids.index(
            "model:llama_1b:b4")
        assert ids.index("attention:b8:s1024") < ids.index(
            "attention:b1:s8192")
        assert ids[-1] == "resize:llama_350m:b8"

    def test_ordering_is_stable_within_tier(self):
        pts = [ok_point("a", risk=5), ok_point("b", risk=5),
               ok_point("c", risk=1)]
        assert [p.point_id for p in ordered(pts)] == ["c", "a", "b"]

    def test_config_hash_tracks_spec(self):
        a = BenchPoint("x", "model", {"model_name": "m", "global_batch_size": 8})
        b = BenchPoint("x", "model", {"model_name": "m", "global_batch_size": 16})
        c = BenchPoint("x", "model", {"global_batch_size": 8, "model_name": "m"})
        assert a.config_hash() != b.config_hash()
        assert a.config_hash() == c.config_hash()  # key order irrelevant

    def test_run_key_changes_with_point_set(self):
        a = [ok_point("a"), ok_point("b")]
        assert run_key_for(a) != run_key_for(a[:1])


class TestWatchdog:
    def test_wedged_point_killed_later_points_complete(self, tmp_path):
        """The acceptance scenario: a hang (the wedged-compile stand-in,
        unkillable from inside on a real chip) is killed by the per-point
        watchdog; every other point still measures; every registered row
        is tagged; there is no whole-stream stall error."""
        points = [
            ok_point("first", {"v": 1}, risk=0),
            BenchPoint("wedge", "debug", {"behavior": "hang", "seconds": 600},
                       risk=5, timeout_seconds=2.0),
            ok_point("after-the-wedge", {"v": 2}, risk=10),
        ]
        summary = orch(points, tmp_path).run()
        assert validate_summary(summary, points) == []
        rows = {r["point_id"]: r for r in summary["rows"]}
        assert rows["first"]["provenance"] == "measured"
        assert rows["after-the-wedge"]["provenance"] == "measured"
        assert rows["wedge"]["provenance"].startswith(
            "skipped:watchdog_timeout")
        assert summary["stats"] == {"total": 3, "measured": 2, "cached": 0,
                                    "skipped": 1}

    def test_budget_exhaustion_eats_the_risky_tail(self, tmp_path):
        """A slow point that consumes the whole budget leaves the later
        (riskier) points tagged budget_exhausted — never silently absent."""
        points = [
            BenchPoint("slow", "debug", {"behavior": "slow", "seconds": 2.0},
                       risk=0, timeout_seconds=30.0),
            ok_point("tail", risk=10),
        ]
        summary = orch(points, tmp_path, total_budget_seconds=2.2).run()
        rows = {r["point_id"]: r for r in summary["rows"]}
        assert rows["tail"]["provenance"].startswith(
            ("skipped:budget_exhausted", "skipped:watchdog_timeout"))
        assert validate_summary(summary, points) == []

    def test_failing_point_isolated(self, tmp_path):
        points = [ok_point("good"),
                  BenchPoint("bad", "debug",
                             {"behavior": "fail", "message": "boom"}, risk=1)]
        summary = orch(points, tmp_path).run()
        rows = {r["point_id"]: r for r in summary["rows"]}
        assert rows["good"]["provenance"] == "measured"
        assert rows["bad"]["provenance"] == "skipped:point_error"
        assert "boom" in rows["bad"]["error"]


class TestCacheBackfill:
    def test_backfill_emits_cached_from(self, tmp_path):
        """A point that fails live back-fills from the last same-config
        measurement with an explicit per-row cached_from tag."""
        flaky = BenchPoint("flaky", "debug",
                           {"behavior": "fail", "message": "transient"})
        cache = ResultCache(os.fspath(tmp_path / "cache.json"))
        cache.put("flaky", flaky.config_hash(), {"mfu": 0.42})

        summary = orch([ok_point("good"), flaky], tmp_path).run()
        rows = {r["point_id"]: r for r in summary["rows"]}
        assert rows["flaky"]["provenance"].startswith("cached_from:")
        assert rows["flaky"]["data"] == {"mfu": 0.42}
        assert "transient" in rows["flaky"]["error"]  # live failure kept
        assert validate_summary(summary, [ok_point("good"), flaky]) == []

    def test_stale_config_does_not_backfill(self, tmp_path):
        """A cached row measured under a DIFFERENT spec must not back-fill
        — stale-config replay is worse than an honest skip."""
        cache = ResultCache(os.fspath(tmp_path / "cache.json"))
        old = BenchPoint("p", "debug", {"behavior": "fail", "message": "x",
                                        "extra": "old-config"})
        cache.put("p", old.config_hash(), {"mfu": 0.99})
        new = BenchPoint("p", "debug", {"behavior": "fail", "message": "x"})
        summary = orch([new], tmp_path).run()
        assert summary["rows"][0]["provenance"] == "skipped:point_error"

    def test_measured_points_written_through_to_cache(self, tmp_path):
        p = ok_point("keeper", {"step_time_ms": 7.0})
        orch([p], tmp_path).run()
        cache = ResultCache(os.fspath(tmp_path / "cache.json"))
        hit = cache.get("keeper", p.config_hash())
        assert hit["data"] == {"step_time_ms": 7.0}
        assert hit["captured_at"]

    def test_corrupt_cache_is_survivable(self, tmp_path):
        (tmp_path / "cache.json").write_text("{not json")
        summary = orch([ok_point("a")], tmp_path).run()
        assert summary["stats"]["measured"] == 1


class TestJournalResume:
    def test_interrupted_run_resumes_without_rerunning(self, tmp_path):
        """Completed points replay from the journal: the resumed run must
        NOT re-execute them. The already-done point is a hang — if resume
        is broken the watchdog fires and the provenance gives it away."""
        done = BenchPoint("expensive", "debug",
                          {"behavior": "hang", "seconds": 600},
                          timeout_seconds=3.0)
        rest = ok_point("remaining", risk=5)
        points = [done, rest]
        # Simulate the interrupted run: run_start + the expensive point's
        # point_done, no run_end (the crash).
        j = RunJournal(os.fspath(tmp_path / "journal.jsonl"),
                       run_key_for(ordered(points)))
        j.open()
        j.point_done("expensive", done.config_hash(), {"mfu": 0.4})
        # no j.end(): the orchestrator died here

        summary = orch(points, tmp_path).run()
        rows = {r["point_id"]: r for r in summary["rows"]}
        assert rows["expensive"]["provenance"] == "measured"
        assert rows["expensive"]["data"] == {"mfu": 0.4}
        assert rows["remaining"]["provenance"] == "measured"

    def test_completed_run_starts_fresh(self, tmp_path):
        """A journal WITH run_end is a finished capture: the next run
        re-measures (same-config staleness is the cache's job, with its
        explicit tag — journal replay must not silently age evidence)."""
        p = ok_point("a", {"v": 1})
        o = orch([p], tmp_path)
        o.run()
        # Second run: journal has run_end, so nothing resumes; the point
        # re-measures (observable: journal now has a fresh run_start).
        summary = orch([p], tmp_path).run()
        assert summary["rows"][0]["provenance"] == "measured"
        lines = [json.loads(line) for line in
                 (tmp_path / "journal.jsonl").read_text().splitlines()]
        assert [x["event"] for x in lines] == [
            "run_start", "point_done", "run_end"]

    def test_different_point_set_invalidates_journal(self, tmp_path):
        old = [ok_point("a")]
        j = RunJournal(os.fspath(tmp_path / "journal.jsonl"),
                       run_key_for(old))
        j.open()
        j.point_done("a", old[0].config_hash(), {"v": 1})
        new_points = [ok_point("a"), ok_point("b")]
        o = orch(new_points, tmp_path)
        assert o.journal.load_resumable() == {}

    def test_torn_final_line_tolerated(self, tmp_path):
        p = ok_point("a")
        path = tmp_path / "journal.jsonl"
        j = RunJournal(os.fspath(path), run_key_for([p]))
        j.open()
        j.point_done("a", p.config_hash(), {"v": 1})
        with open(path, "a") as f:
            f.write('{"event": "point_done", "point_id": "tor')  # the crash
        resumable = RunJournal(os.fspath(path),
                               run_key_for([p])).load_resumable()
        assert resumable["a"]["data"] == {"v": 1}


class TestSummaryContract:
    def test_validate_summary_catches_gaps(self):
        points = [ok_point("a"), ok_point("b")]
        summary = {"rows": [
            {"point_id": "a", "provenance": "measured", "data": {}}]}
        problems = validate_summary(summary, points)
        assert any("missing row for b" in p for p in problems)

    def test_validate_summary_catches_untagged(self):
        points = [ok_point("a")]
        summary = {"rows": [{"point_id": "a", "provenance": "", "data": {}}]}
        assert any("untagged" in p
                   for p in validate_summary(summary, points))

    def test_to_hardware_section_shapes(self, tmp_path):
        points = [
            BenchPoint("meta", "debug",
                       {"behavior": "ok", "data": {"backend": "fake"}},
                       risk=-1, section="meta"),
            BenchPoint("model:m:b8", "debug",
                       {"behavior": "ok", "data": {"model": "m", "batch": 8,
                                                   "mfu": 0.4}},
                       section="model"),
            BenchPoint("attention:b2:s128", "debug",
                       {"behavior": "fail"}, section="attention"),
        ]
        hw = to_hardware_section(orch(points, tmp_path).run())
        assert hw["backend"] == "fake"
        assert hw["meta_provenance"] == "measured"
        assert hw["models"][0]["mfu"] == 0.4
        assert hw["models"][0]["provenance"] == "measured"
        att = hw["attention"][0]
        assert att["provenance"].startswith("skipped:")
        assert "error" in att
        assert hw["benchrunner"]["stats"]["skipped"] == 1


class TestSilentEmptyAttentionRegression:
    """BENCH_r05 shipped `attention: []` when the stream wedged — absence
    indistinguishable from not-configured. The contract now: a wedged
    point costs its row (skipped:watchdog_timeout), budget exhaustion
    tags the rest, and EVERY registered attention shape appears in the
    artifact with its reason, even with no cache to fall back on."""

    SHAPES = [(8, 1024), (8, 4096), (1, 8192)]

    def _attention_points(self, wedge_first: bool):
        pts = []
        for i, (b, s) in enumerate(self.SHAPES):
            # batch/seq ride in the spec like real attention points —
            # that spec is what identifies a skipped row in the artifact.
            spec = ({"behavior": "hang", "seconds": 600,
                     "batch": b, "seq": s}
                    if (wedge_first and i == 0) else
                    {"behavior": "ok", "batch": b, "seq": s,
                     "data": {"batch": b, "seq": s, "flash_ms": 1.0}})
            pts.append(BenchPoint(f"attention:b{b}:s{s}", "debug", spec,
                                  risk=i, section="attention",
                                  timeout_seconds=2.0))
        return pts

    def test_wedged_point_leaves_skipped_rows_for_every_shape(self, tmp_path):
        """The injected wedge eats the whole budget: its row is a
        watchdog kill, the remaining shapes are budget_exhausted — and
        the artifact carries all three, none silently absent."""
        points = self._attention_points(wedge_first=True)
        summary = orch(points, tmp_path, total_budget_seconds=6.0).run()
        assert validate_summary(summary, points) == []
        hw = to_hardware_section(summary)
        assert len(hw["attention"]) == len(self.SHAPES)
        by_shape = {(a.get("batch"), a.get("seq")): a
                    for a in hw["attention"]}
        assert set(by_shape) == set(self.SHAPES)
        for shape, row in by_shape.items():
            assert row["provenance"].startswith("skipped:"), (shape, row)
        assert by_shape[self.SHAPES[0]]["provenance"].startswith(
            "skipped:watchdog_timeout")

    def test_bench_fallback_keeps_skipped_rows_without_cache(self, tmp_path):
        """bench.py's nothing-measured path: with no last-good cache the
        artifact must still be the summary's provenance-tagged rows plus
        the error — never a bare error with an empty attention list."""
        import sys
        sys.path.insert(0, REPO)
        from bench import _cached_fallback

        points = self._attention_points(wedge_first=True)
        summary = orch(points, tmp_path, total_budget_seconds=6.0).run()
        assert summary["stats"]["measured"] == 0
        out = _cached_fallback(os.fspath(tmp_path / "no-cache-here"),
                               "no point measured", summary=summary)
        assert out["error"] == "no point measured"
        assert len(out["attention"]) == len(self.SHAPES)
        assert all(a["provenance"].startswith("skipped:")
                   for a in out["attention"])


def test_bench_dryrun_end_to_end(tmp_path):
    """`make bench-dryrun`, in-process: the orchestrator runs end-to-end
    on the fake backend (real subprocess workers, a real watchdog kill)
    and the artifact validates with zero problems."""
    from vodascheduler_tpu.benchrunner.dryrun import run_dryrun

    result = run_dryrun(workdir=os.fspath(tmp_path))
    assert result["ok"], result["problems"]
    assert result["stats"]["measured"] == 4
    assert result["stats"]["skipped"] == 2
    hw = result["hardware"]
    assert {m["provenance"] for m in hw["models"]} == {
        "measured", "skipped:watchdog_timeout(2s)"}
    assert hw["resize"][0]["provenance"] == "measured"


@pytest.mark.slow
def test_worker_runs_real_tiny_attention_point_on_cpu(monkeypatch, tmp_path):
    """The real (jax) worker path, hermetically: one tiny attention point
    through the full subprocess isolation machinery. On an image whose
    jax predates the kernels (the known seed-env skew that also fails
    test_smoke_fast's flash parity), the contract still holds: the point
    is isolated and honestly tagged skipped:point_error — never a hang,
    never an untagged gap."""
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("VODA_HWBENCH_ON_CPU", "1")
    points = [BenchPoint("attention:b1:s64", "attention",
                         {"batch": 1, "seq": 64, "heads": 2, "head_dim": 8},
                         timeout_seconds=560.0)]
    summary = orch(points, tmp_path).run()
    assert validate_summary(summary, points) == []
    row = summary["rows"][0]
    if row["provenance"] == "measured":
        assert row["data"]["flash_ms"] > 0
        assert row["data"]["xla_ms"] > 0
    else:
        assert row["provenance"] == "skipped:point_error", row
        assert row["error"]
