"""Elastic-resize cost bench (runtime/resize_bench.py), hermetically.

The measurement itself is meaningful only on hardware; these tests pin
the machinery — two sequential children, cross-process mark stitching,
per-phase segments, the replay-facing resize_cost_seconds rollup — on
the CPU platform with a tiny model.
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow  # spawns two jax subprocesses (~90 s)


def test_resize_cost_breakdown_tiny(monkeypatch, tmp_path):
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("VODA_HWBENCH_ON_CPU", "1")
    from vodascheduler_tpu.runtime.resize_bench import bench_resize_cost

    # mnist_mlp, not llama_tiny: the machinery is model-agnostic, and
    # llama-family TrainSessions are broken on images whose jax predates
    # get_abstract_mesh (the known seed-env skew test_smoke_fast pins).
    out = bench_resize_cost("mnist_mlp", 2, warm_steps=2,
                            workdir=os.fspath(tmp_path))
    assert out["model"] == "mnist_mlp"
    assert out["backend"] == "cpu"
    assert out["checkpoint_bytes"] > 100_000
    # Async initiate must cost less than the full drain (the point of
    # overlapping the shard writes with training).
    assert 0 < out["save_async_initiate_ms"]
    assert 0 < out["save_sync_ms"]
    seg = out["restart_segments_ms"]
    for mark in ("proc_start_ms", "backend_ready_ms", "restored_ms",
                 "first_step_done_ms"):
        assert seg[mark] >= 0, seg
    # Total restart is the sum of its segments (same monotonic clock).
    assert abs(sum(seg.values()) - out["restart_total_ms"]) < 1.0
    assert out["resize_cost_seconds"] > 0
    # Two-tier contract (doc/elastic-resize.md): both paths reported,
    # and the in-process fast path strictly cheaper than the cold
    # checkpoint-restart for the same point — the fast path skips the
    # save, the process lifecycle, and the restore.
    paths = {p["path"]: p for p in out["resize_paths"]}
    assert set(paths) == {"fast", "cold"}
    assert out["fast_resize_ms"] > 0
    assert paths["fast"]["seconds"] > 0
    assert paths["cold"]["seconds"] == out["resize_cost_seconds"]
    assert paths["fast"]["seconds"] < paths["cold"]["seconds"]
    assert paths["fast"]["from_chips"] == 1
    assert paths["fast"]["to_chips"] in (1, 2)


def test_stream_mode_emits_resize_lines(monkeypatch, tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu", VODA_HWBENCH_ON_CPU="1")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-m", "vodascheduler_tpu.runtime.resize_bench",
         json.dumps({"stream": True, "points": [["mnist_mlp", 2]]})],
        capture_output=True, text=True, timeout=560, env=env, cwd=repo)
    assert r.returncode == 0, r.stderr[-500:]
    sys.path.insert(0, repo)
    from bench import parse_hw_stream
    out = parse_hw_stream(r.stdout)
    assert out["resize"][0]["model"] == "mnist_mlp"
    assert out["resize"][0]["restart_total_ms"] > 0
