"""The elastic-resize fast path (doc/elastic-resize.md), hermetically.

Tier A: reshard_state()/TrainSession.resize() round-trips (state must be
bit-identical across grow/shrink, including uneven chip counts), and the
scheduler driving a live in-place resize end-to-end on the fake backend
through the real-time pump() path — counted as a resize, not a restart,
with the preemption lease left alone. Tier B: the VODA_COMPILE_CACHE_DIR
env knob (set → jax persistent cache configured; unset → jax untouched),
checked in subprocesses because the configuration is process-global.
"""

import heapq
import itertools
import os
import subprocess
import sys

import jax
import numpy as np

from vodascheduler_tpu.allocator import ResourceAllocator
from vodascheduler_tpu.cluster.backend import ResizePath
from vodascheduler_tpu.cluster.fake import FakeClusterBackend
from vodascheduler_tpu.common.clock import Clock
from vodascheduler_tpu.common.events import EventBus
from vodascheduler_tpu.common.job import JobConfig, JobSpec
from vodascheduler_tpu.common.store import JobStore
from vodascheduler_tpu.models import get_model
from vodascheduler_tpu.parallel.mesh import MeshPlan
from vodascheduler_tpu.placement import PlacementManager
from vodascheduler_tpu.runtime.train import TrainSession
from vodascheduler_tpu.scheduler import Scheduler
from vodascheduler_tpu.service import AdmissionService

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _host_state(session):
    return jax.tree.map(np.asarray, session.state)


def _assert_bitwise_equal(a, b, context):
    eq = jax.tree.map(np.array_equal, a, b)
    bad = [p for p, ok in jax.tree_util.tree_flatten_with_path(eq)[0]
           if not ok]
    assert not bad, f"{context}: leaves changed across resize: {bad}"


class TestReshardRoundTrip:
    """Satellite: grow-then-shrink round trips. A live resize is pure
    data movement — every param and optimizer-state leaf must survive
    bit-exactly, and the resized session must still train."""

    def test_grow_then_shrink_bitwise(self):
        # Explicit fsdp/tp plans so real (non-replicated) resharding
        # happens, not just mesh relabeling.
        s = TrainSession(get_model("mnist_mlp"), 2, global_batch_size=8,
                         devices=jax.devices()[:2],
                         plan=MeshPlan(dp=1, fsdp=2))
        s.run_steps(2)
        step_before = s.step

        snap = _host_state(s)
        s.resize(8, plan=MeshPlan(dp=2, fsdp=2, tp=2))
        _assert_bitwise_equal(snap, _host_state(s), "grow 2->8")
        assert s.num_chips == 8 and s.step == step_before

        snap = _host_state(s)
        s.resize(4, plan=MeshPlan(dp=1, fsdp=4))
        _assert_bitwise_equal(snap, _host_state(s), "shrink 8->4")

        # Still trains at the new size (jitted step rebuilt and usable).
        loss = s.run_steps(1)
        assert np.isfinite(loss)
        assert s.step == step_before + 1

    def test_uneven_chip_counts(self):
        """Non-power-of-two targets: axes that stop dividing fall back to
        replication (sharding._fit_spec) — values still bit-identical."""
        s = TrainSession(get_model("mnist_mlp"), 2, global_batch_size=12,
                         devices=jax.devices()[:2],
                         plan=MeshPlan(dp=1, fsdp=2))
        s.run_steps(1)
        for target in (3, 6, 4):  # 3 divides nothing in the model dims
            snap = _host_state(s)
            s.resize(target)
            _assert_bitwise_equal(snap, _host_state(s), f"resize->{target}")
            assert np.isfinite(s.run_steps(1))

    def test_resize_beyond_devices_raises(self):
        s = TrainSession(get_model("mnist_mlp"), 1, global_batch_size=8,
                         devices=jax.devices()[:1])
        try:
            s.resize(99)
        except ValueError as e:
            assert "checkpoint-restart" in str(e)
        else:
            raise AssertionError("resize past visible devices must raise")


class _ManualClock(Clock):
    """Wall-clock stand-in the test advances by hand. Deliberately NOT a
    VirtualClock: the scheduler then runs in real-time mode, where the
    service daemon's pump() is what executes pending rescheds — the path
    this test must drive."""

    def __init__(self, start: float = 1753760000.0):
        self._now = start
        self._timers = []
        self._seq = itertools.count()

    def now(self) -> float:
        return self._now

    def call_at(self, when, fn) -> None:
        heapq.heappush(self._timers, (when, next(self._seq), fn))

    def call_later(self, delay, fn) -> None:
        self.call_at(self._now + delay, fn)

    def tick(self, seconds: float) -> None:
        target = self._now + seconds
        while self._timers and self._timers[0][0] <= target:
            when, _, fn = heapq.heappop(self._timers)
            self._now = max(self._now, when)
            fn()
        self._now = target


class TestSchedulerInplaceResizeE2E:
    """Satellite: a live in-place resize end-to-end through
    Scheduler.pump() on the fake backend — same-host shrink reshards in
    place: new counter, no restart counted, lease not re-armed, and the
    job's simulated incarnation never restarts (the fake-backend
    equivalent of 'no checkpoint written')."""

    def _world(self):
        clock = _ManualClock()
        store = JobStore()
        bus = EventBus()
        backend = FakeClusterBackend(clock, restart_overhead_seconds=50.0,
                                     inplace_overhead_seconds=2.0)
        backend.add_host("host-0", 8, announce=False)
        pm = PlacementManager("pool")
        sched = Scheduler("pool", backend, store, ResourceAllocator(store),
                          clock, bus=bus, placement_manager=pm,
                          algorithm="ElasticFIFO", rate_limit_seconds=5.0)
        admission = AdmissionService(store, bus, clock)
        return clock, store, backend, sched, admission

    def test_pump_drives_inplace_resize(self):
        clock, store, backend, sched, admission = self._world()
        a = admission.create_training_job(JobSpec(
            name="stretchy", pool="pool",
            config=JobConfig(min_num_chips=1, max_num_chips=8, epochs=100)))
        assert sched.job_num_chips[a] == 8  # started with the whole host
        sim = backend.jobs[a]
        assert sim.restarts == 1 and sim.resizes_inplace == 0

        # A lease the resize must NOT re-arm.
        job = store.get_job(a)
        job.metrics.seconds_since_restart = 777.0

        # Second submission inside the rate window: resched goes pending;
        # in real-time mode only pump() may run it.
        b = admission.create_training_job(JobSpec(
            name="newcomer", pool="pool",
            config=JobConfig(min_num_chips=1, max_num_chips=4, epochs=100)))
        assert sched.resched_pending
        assert sched.job_num_chips[a] == 8  # nothing applied yet

        clock.tick(6.0)  # open the rate-limit window
        sched.pump()

        # a shrank on its own host -> in-place; b started (a restart).
        assert sched.job_num_chips[a] == 4
        assert sched.job_num_chips[b] == 4
        assert backend.resizes_inplace_total == 1
        assert backend.cold_resizes_total == 0
        assert sim.resizes_inplace == 1
        assert sim.restarts == 1  # the original start only: never restarted
        assert sched.m_job_resizes_inplace.value() == 1
        assert sched.m_job_restarts.value() == 2  # two starts, no resize
        # The in-place pause is the fast-path cost, not the 50 s restart.
        assert 0 < sim.busy_until - clock.now() <= 2.0
        # Lease untouched: still counting from the last COLD restart.
        assert store.get_job(a).metrics.seconds_since_restart >= 777.0

    def test_migration_stays_cold(self):
        """A host-set change is a process-group change: the fake must
        price it as a cold restart and the scheduler must count it as
        one (lease re-armed)."""
        clock, store, backend, sched, admission = self._world()
        backend.add_host("host-1", 8, announce=False)
        a = admission.create_training_job(JobSpec(
            name="mover", pool="pool",
            config=JobConfig(min_num_chips=1, max_num_chips=8, epochs=100)))
        sim = backend.jobs[a]
        path = backend.scale_job(a, 8, [("host-1", 8)])
        assert path == ResizePath.RESTART
        assert backend.cold_resizes_total == 1
        assert backend.resizes_inplace_total == 0
        assert sim.restarts == 2


class TestCompileCacheEnvKnob:
    """Satellite: VODA_COMPILE_CACHE_DIR set → the supervisor-side helper
    points jax_compilation_cache_dir at it (and entries actually land on
    the CPU backend); unset → jax's configuration is untouched. Run in
    subprocesses: the jax config is process-global."""

    CODE = (
        "import os, json, jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from vodascheduler_tpu.runtime.compile_cache import ("
        "configure_compilation_cache)\n"
        "before = jax.config.jax_compilation_cache_dir\n"
        "ret = configure_compilation_cache()\n"
        "jax.jit(lambda x: x * 3)(jax.numpy.ones(()))\n"
        "print(json.dumps({'before': before, 'ret': ret,\n"
        "    'after': jax.config.jax_compilation_cache_dir}))\n"
    )

    def _run(self, env):
        env = dict(env, JAX_PLATFORMS="cpu")
        r = subprocess.run([sys.executable, "-c", self.CODE],
                           capture_output=True, text=True, timeout=120,
                           env=env, cwd=REPO)
        assert r.returncode == 0, r.stderr[-800:]
        import json
        return json.loads(r.stdout.strip().splitlines()[-1])

    def test_set_configures_and_populates(self, tmp_path):
        cache_dir = os.fspath(tmp_path / "xla-cache")
        out = self._run(dict(os.environ, VODA_COMPILE_CACHE_DIR=cache_dir))
        assert out["ret"] == cache_dir
        assert out["after"] == cache_dir
        assert os.listdir(cache_dir), "no persistent cache entries written"

    def test_unset_leaves_jax_untouched(self, tmp_path):
        env = {k: v for k, v in os.environ.items()
               if k != "VODA_COMPILE_CACHE_DIR"}
        out = self._run(env)
        assert out["ret"] is None
        assert out["after"] == out["before"]  # untouched, whatever default
