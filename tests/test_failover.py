"""Hot-standby failover (doc/durability.md "Hot standby"): journal
shipping (tailer framing/resync/fetch), the incremental StandbyApplier,
warm takeover, the recovery fastpath's equivalence to its reference
oracle, tombstone retention, the trainer-side placement-context CSV
round trip, and the committed schema-9 failover artifact pins."""

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from vodascheduler_tpu.allocator import ResourceAllocator
from vodascheduler_tpu.cluster.fake import FakeClusterBackend, WorkloadProfile
from vodascheduler_tpu.common.clock import VirtualClock
from vodascheduler_tpu.common.events import EventBus
from vodascheduler_tpu.common.job import JobConfig, JobSpec, TrainingJob
from vodascheduler_tpu.common.store import JobStore
from vodascheduler_tpu.common.types import JobStatus
from vodascheduler_tpu.durability.journal import (
    FencedOut,
    Journal,
    MemoryStorage,
    parse_suffix,
)
from vodascheduler_tpu.durability.leader import FileLease, MemoryLease
from vodascheduler_tpu.durability.recover import (
    StandbyApplier,
    logical_tables,
    read_state,
    read_states_parallel,
    recover_scheduler,
)
from vodascheduler_tpu.durability.shipping import (
    FileTailSource,
    HttpTailSource,
    JournalTailer,
    StorageTailSource,
)
from vodascheduler_tpu.durability.standby import PoolStandby, finish_takeover
from vodascheduler_tpu.obs import audit as obs_audit
from vodascheduler_tpu.obs import tracer as obs_tracer
from vodascheduler_tpu.placement import PlacementManager
from vodascheduler_tpu.scheduler import Scheduler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_world(journal=None, hosts=2, chips=4, resume=False,
               clock=None, store=None, backend=None, bus=None,
               tracer=None, recovered_state=None):
    clock = clock or VirtualClock(start=1000.0)
    tracer = tracer or obs_tracer.Tracer(clock=clock, ring_size=256)
    store = store if store is not None else JobStore()
    bus = bus or EventBus()
    if backend is None:
        backend = FakeClusterBackend(clock, restart_overhead_seconds=2.0)
        for i in range(hosts):
            backend.add_host(f"host-{i}", chips, announce=False)
    pm = PlacementManager("p")
    sched = Scheduler("p", backend, store, ResourceAllocator(store),
                      clock, bus=bus, placement_manager=pm,
                      rate_limit_seconds=1.0, profile_cpu=False,
                      tracer=tracer, journal=journal, resume=resume,
                      recovered_state=recovered_state)
    return clock, store, backend, bus, tracer, sched


def submit(sched, store, backend, clock, name, min_chips=1, max_chips=4,
           epochs=2):
    spec = JobSpec(name=name, pool="p",
                   config=JobConfig(min_num_chips=min_chips,
                                    max_num_chips=max_chips,
                                    epochs=epochs))
    backend.register_profile(name,
                             WorkloadProfile(epoch_seconds_at_1=8.0))
    store.insert_job(TrainingJob.from_spec(spec, submit_time=clock.now()))
    sched.create_training_job(name)


# ---- shipping: the streaming tailer ----------------------------------------


class TestShipping:
    def _journal(self, n=5):
        s = MemoryStorage()
        j = Journal(storage=s)
        for i in range(n):
            j.append("jbook", {"op": "commit", "job": f"j{i}",
                               "chips": i + 1})
        return s, j

    def test_steady_tail_feeds_in_order(self):
        s, j = self._journal(3)
        fed = []
        tailer = JournalTailer(StorageTailSource(s), fed.append)
        assert tailer.poll() == 3
        assert [r["job"] for r in fed] == ["j0", "j1", "j2"]
        assert tailer.poll() == 0  # idle: nothing consumed twice
        j.append("jbook", {"op": "commit", "job": "late", "chips": 1})
        assert tailer.poll() == 1
        assert fed[-1]["job"] == "late"

    def test_partial_frame_waits_never_drops(self):
        s, j = self._journal(2)
        fed = []
        tailer = JournalTailer(StorageTailSource(s), fed.append)
        tailer.poll()
        # A frame arriving in two halves (the leader's append in
        # flight): the first poll must consume NOTHING of it.
        before = len(s.data)
        j.append("jbook", {"op": "commit", "job": "half", "chips": 2})
        whole = bytes(s.data[before:])
        s.data = s.data[:before + len(whole) // 2]
        assert tailer.poll() == 0
        s.data = bytearray(bytes(s.data) + whole[len(whole) // 2:])
        assert tailer.poll() == 1
        assert fed[-1]["job"] == "half"

    def test_resync_after_compaction_fold(self):
        """A compaction rewrite (segment truncated, snapshot ahead)
        must resync: the applier ends exactly equal to a batch
        replay."""
        s, j = self._journal(6)
        applier = StandbyApplier()
        tailer = JournalTailer(StorageTailSource(s), applier.apply,
                               bootstrap=applier.bootstrap)
        tailer.poll()
        assert j.maybe_compact(force=True)
        j.append("jbook", {"op": "commit", "job": "post", "chips": 7})
        tailer.poll()
        assert tailer.resyncs >= 1
        want = read_state(j)
        assert applier.state.booked == want.booked
        assert applier.state.last_seq == want.last_seq

    def test_resync_bootstraps_newer_snapshot(self):
        """A fresh standby attaching to a folded journal must take the
        snapshot (records before the fold never existed as frames)."""
        s, j = self._journal(4)
        j.maybe_compact(force=True)
        applier = StandbyApplier()
        tailer = JournalTailer(StorageTailSource(s), applier.apply,
                               bootstrap=applier.bootstrap)
        tailer.poll()
        want = read_state(j)
        assert applier.state.booked == want.booked
        assert applier.state.granted == want.granted

    def test_torn_tail_waits_then_trim_resyncs(self):
        s, j = self._journal(3)
        fed = []
        tailer = JournalTailer(StorageTailSource(s), fed.append)
        tailer.poll()
        j.append("jbook", {"op": "commit", "job": "torn", "chips": 1})
        s.data = s.data[:-4]  # the crash's half-written frame
        assert tailer.poll() == 0  # waits — could be an append in flight
        # Leader restart trims the torn tail (shrink) and appends anew.
        j2 = Journal(storage=s, epoch=2)
        assert j2.torn_trimmed == 1
        j2.append("jbook", {"op": "commit", "job": "fresh", "chips": 2})
        tailer.poll()
        assert [r["job"] for r in fed if r["job"] in ("torn", "fresh")] \
            == ["fresh"]

    def test_seq_gap_at_aliased_offset_forces_resync(self):
        """A fold that shrinks the segment then REGROWS it past the
        tailer's offset between two polls can land the stale offset on
        a frame boundary of the new generation — the frames parse
        cleanly but would silently skip everything in between. The seq
        continuity guard must force a resync instead."""
        import json as _json

        from vodascheduler_tpu.durability.journal import frame

        s, j = self._journal(3)
        applier = StandbyApplier()
        tailer = JournalTailer(StorageTailSource(s), applier.apply,
                               bootstrap=applier.bootstrap)
        tailer.poll()
        stale_offset = tailer.offset
        assert applier.last_seq == 3

        def frame_of(pad_to):
            """One valid frame of exactly pad_to bytes (grow the pad
            field one byte at a time)."""
            pad = ""
            while True:
                payload = _json.dumps(
                    {"k": "jclock", "seq": 8, "epoch": 1,
                     "job": "filler", "at": 0.0, "pad": pad},
                    separators=(",", ":")).encode()
                line = frame(payload)
                if len(line) == pad_to:
                    return line
                assert len(line) < pad_to, "overshot the target size"
                pad += "x"

        # The rewritten generation: a snapshot covering seqs <= 9, one
        # filler frame of EXACTLY stale_offset bytes, then fresh frames
        # at seqs 10-11 — so the stale offset aliases a frame boundary
        # and parses cleanly with a seq gap (expected next was 4).
        filler = frame_of(stale_offset)
        assert len(filler) == stale_offset
        fresh = (
            frame(_json.dumps({"k": "jbook", "op": "commit",
                               "job": "after-fold", "chips": 2,
                               "seq": 10, "epoch": 1},
                              separators=(",", ":")).encode())
            + frame(_json.dumps({"k": "jclock", "job": "after-fold",
                                 "at": 1.0, "seq": 11, "epoch": 1},
                                separators=(",", ":")).encode()))
        s.snapshot = {"last_seq": 9, "epoch": 1, "schema": 1,
                      "booked": {"folded": 4}, "granted": ["folded"]}
        s.replace(filler + fresh)
        tailer.poll()
        assert tailer.resyncs >= 1, "seq gap must force a resync"
        # Post-resync the applier took the snapshot AND the suffix —
        # nothing between the fold and the alias was silently skipped.
        assert applier.state.booked == {"folded": 4, "after-fold": 2}
        assert applier.state.last_seq == 11

    def test_crc_valid_but_not_json_is_corruption_not_crash(self):
        """A payload that passes its checksum but is not JSON was never
        written by this journal: it must surface through the corruption
        taxonomy (JournalCorrupt from records(), a problem from fsck) —
        never an uncaught decoder error."""
        from vodascheduler_tpu.durability.journal import (
            JournalCorrupt,
            frame,
            parse_frames,
        )

        s, j = self._journal(2)
        s.data.extend(frame(b"not json at all"))
        s.data.extend(frame(b'{"k":"jclock","seq":9,"epoch":1,'
                            b'"job":"later","at":0.0}'))
        records, torn, corrupt = parse_frames(bytes(s.data))
        assert corrupt is not None and "not valid JSON" in corrupt
        assert len(records) == 2  # the clean prefix is kept
        with pytest.raises(JournalCorrupt):
            Journal(storage=s).records()

    def test_parse_suffix_waits_on_incomplete(self):
        s, j = self._journal(1)
        data = bytes(s.data)
        records, consumed, corrupt = parse_suffix(data[:-3])
        assert records == [] and consumed == 0 and corrupt is None
        records, consumed, corrupt = parse_suffix(data)
        assert len(records) == 1 and consumed == len(data)

    def test_http_fetch_path(self):
        """The cross-host shipped-segment fetch: a standby bootstraps
        from GET /journal/snapshot and follows GET /journal/segment
        through the scheduler REST surface."""
        from vodascheduler_tpu.common.metrics import Registry
        from vodascheduler_tpu.service.rest import make_scheduler_server

        storage = MemoryStorage()
        jnl = Journal(storage=storage)
        clock, store, backend, bus, tracer, sched = make_world(journal=jnl)
        submit(sched, store, backend, clock, "web0")
        jnl.maybe_compact(force=True)
        submit(sched, store, backend, clock, "web1")
        server = make_scheduler_server({"p": sched}, Registry(),
                                       host="127.0.0.1", port=0)
        server.start()
        try:
            source = HttpTailSource(f"http://127.0.0.1:{server.port}",
                                    "p")
            applier = StandbyApplier()
            tailer = JournalTailer(source, applier.apply,
                                   bootstrap=applier.bootstrap)
            tailer.poll()
            want = read_state(jnl)
            assert applier.state.statuses == want.statuses
            assert applier.state.booked == want.booked
            assert applier.state.last_seq == want.last_seq
        finally:
            server.stop()
        sched.stop()


# ---- the incremental applier ------------------------------------------------


class TestStandbyApplier:
    def test_incremental_equals_batch_at_every_prefix(self):
        s = MemoryStorage()
        j = Journal(storage=s)
        applier = StandbyApplier()
        for i in range(20):
            if i % 5 == 4:
                j.append("jretire", {"job": f"j{i - 1}",
                                     "status": "Canceled"})
            else:
                j.append("jbook", {"op": "commit", "job": f"j{i}",
                                   "chips": 1 + i % 3})
            rec = j.records()[-1]
            applier.apply(rec)
            ref = StandbyApplier()
            for r in j.records():
                ref.apply(r)
            assert applier.state.booked == ref.state.booked
            assert applier.state.retired == ref.state.retired
            assert applier.state.granted == ref.state.granted
            assert applier.state.last_seq == ref.state.last_seq

    def test_bootstrap_older_snapshot_ignored(self):
        s = MemoryStorage()
        j = Journal(storage=s)
        for i in range(4):
            j.append("jbook", {"op": "commit", "job": f"j{i}", "chips": 1})
        applier = StandbyApplier()
        for r in j.records():
            applier.apply(r)
        assert not applier.bootstrap({"last_seq": 2, "booked": {}})
        assert applier.state.booked  # untouched

    def test_stale_epoch_records_dropped(self):
        applier = StandbyApplier()
        applier.apply({"k": "jbook", "op": "commit", "job": "a",
                       "chips": 2, "seq": 1, "epoch": 3})
        assert not applier.apply({"k": "jbook", "op": "commit",
                                  "job": "a", "chips": 9, "seq": 2,
                                  "epoch": 1})
        assert applier.state.booked == {"a": 2}
        assert applier.state.stale_records == 1


# ---- batch append + warm open ----------------------------------------------


class TestBatchAndWarmOpen:
    def test_batch_flushes_once_and_reads_back(self):
        s = MemoryStorage()
        j = Journal(storage=s)
        appends_before = len(s.data)

        class CountingStorage:
            def __init__(self, inner):
                self.inner = inner
                self.appends = 0

            def __getattr__(self, item):
                return getattr(self.inner, item)

            def append(self, line):
                self.appends += 1
                self.inner.append(line)

        j.storage = counting = CountingStorage(s)
        with j.batch() as batch:
            for i in range(10):
                j.append("jclock", {"job": f"j{i}", "at": float(i)})
            assert len(s.data) == appends_before  # nothing landed yet
            assert len(batch.records) == 10
        assert counting.appends == 1
        assert [r["job"] for r in j.records()] \
            == [f"j{i}" for i in range(10)]

    def test_batch_fence_at_boundary_drops_buffer(self):
        lease = MemoryLease()
        s = MemoryStorage()
        j = Journal(storage=s, epoch=lease.epoch,
                    fence=lease.current_epoch)
        with pytest.raises(FencedOut):
            with j.batch():
                j.append("jclock", {"job": "a", "at": 1.0})
                lease.advance_epoch()  # deposed mid-batch
        assert j.fenced
        assert s.size() == 0  # the buffer never landed

    def test_batch_consume_suppresses_flush(self):
        s = MemoryStorage()
        j = Journal(storage=s)
        with j.batch() as batch:
            j.append("jclock", {"job": "a", "at": 1.0})
            records = batch.consume()
        assert s.size() == 0
        assert records[0]["job"] == "a" and records[0]["seq"] == 1

    def test_warm_open_trims_torn_tail_and_resumes_seq(self):
        s = MemoryStorage()
        j = Journal(storage=s)
        for i in range(3):
            j.append("jbook", {"op": "commit", "job": f"j{i}", "chips": 1})
        clean = s.size()
        s.data.extend(b"123 deadbeef {tor")  # the dead leader's torn tail
        j2 = Journal(storage=s, epoch=2,
                     resume_hint={"last_seq": 3, "clean_bytes": clean})
        assert s.size() == clean
        assert j2.torn_trimmed == 1
        j2.append("jbook", {"op": "commit", "job": "next", "chips": 2})
        state = read_state(j2)
        assert state.last_seq == 4
        assert state.booked == {"j0": 1, "j1": 1, "j2": 1, "next": 2}


# ---- warm takeover ----------------------------------------------------------


class TestWarmTakeover:
    def test_takeover_from_warm_standby(self, tmp_path):
        """The full protocol on a real file journal + file lease: the
        standby applies continuously, the leader dies, and the warm
        takeover (acquire -> suffix drain -> warm open -> reconcile ->
        first pass) reproduces exactly what a cold recovery would."""
        clock = VirtualClock(start=1000.0)
        lease = FileLease(str(tmp_path / "lease"), holder="A",
                          ttl_seconds=10.0, clock=clock)
        lease.try_acquire()
        path = str(tmp_path / "p.wal")
        jnl = Journal(path=path, epoch=lease.epoch,
                      fence=lease.current_epoch, clock=clock)
        _, store, backend, bus, tracer, sched = make_world(
            journal=jnl, clock=clock)
        standby = PoolStandby("p", FileTailSource(path))
        submit(sched, store, backend, clock, "j0", epochs=1000)
        clock.advance(2)
        standby.poll()
        submit(sched, store, backend, clock, "j1", epochs=1000)
        clock.advance(2)
        # j1's records are the suffix the takeover must drain.
        pre = logical_tables(sched)
        sched.stop()
        lease.release()
        holder = FileLease(str(tmp_path / "lease"), holder="B",
                           ttl_seconds=10.0, clock=clock)
        t0 = time.monotonic()
        epoch = holder.try_acquire()
        bundle = standby.prepare_takeover()
        assert bundle["suffix_records"] > 0  # a real drain happened
        jnl2 = Journal(path=path, epoch=epoch,
                       fence=holder.current_epoch, clock=clock,
                       resume_hint=bundle["resume_hint"])
        _, _, _, _, _, sched2 = make_world(
            journal=jnl2, resume=True, clock=clock, store=store,
            backend=backend, bus=bus, tracer=tracer,
            recovered_state=bundle["state"])
        rec = finish_takeover(sched2, standby, t0, epoch,
                              bundle["suffix_records"])
        # Exact: the warm takeover rebuilt the pre-crash tables.
        assert sched2._recovered_tables == pre
        assert sched2._last_recovery_report["divergences"] == []
        # The takeover_report validates against its closed schema and
        # lands on the /debug/standby surface.
        assert not obs_audit.validate_record(rec)
        assert sched2._last_takeover["epoch"] == epoch
        assert sched2._last_takeover["suffix_records"] \
            == bundle["suffix_records"]
        # The deposed leader's next pass probes the lease and stops
        # WITHOUT touching the backend (the no-op-delta fencing hole).
        assert sched.journal.probe_fence()
        # And the new leader keeps scheduling.
        clock.advance(30)
        assert sched2.ready_jobs["j0"].status == JobStatus.RUNNING
        sched2.stop()

    def test_debug_standby_route(self, tmp_path):
        from vodascheduler_tpu.common.metrics import Registry
        from vodascheduler_tpu.service.rest import make_scheduler_server

        clock, store, backend, bus, tracer, sched = make_world()
        sched._last_takeover = {"epoch": 2, "duration_ms": 123.4,
                                "suffix_records": 1, "divergences": 0}
        server = make_scheduler_server(
            {"p": sched}, Registry(), host="127.0.0.1", port=0,
            standby_stats=lambda: [{"pool": "p", "applied_seq": 7}])
        handler = server.routes[("GET", "/debug/standby")]
        status, payload = handler(b"", {})[:2]
        assert status == 200
        assert payload["takeovers"]["p"]["duration_ms"] == 123.4
        assert payload["standby"][0]["applied_seq"] == 7
        sched.stop()


# ---- recovery fastpath == reference oracle ---------------------------------


class TestRecoveryFastpathOracle:
    def _crashed_world(self, storage, lease):
        jnl = Journal(storage=storage, epoch=lease.epoch,
                      fence=lease.current_epoch)
        clock, store, backend, bus, tracer, sched = make_world(journal=jnl)
        for name in ("a0", "a1", "a2"):
            submit(sched, store, backend, clock, name, epochs=1000)
        clock.advance(3)
        sched.delete_training_job("a1")
        clock.advance(3)
        sched.stop()
        return clock, store, backend, bus, tracer, sched

    def test_fastpath_rebuilds_identical_tables(self):
        results = {}
        for fastpath in (False, True):
            storage = MemoryStorage()
            lease = MemoryLease()
            (clock, store, backend, bus, tracer,
             sched) = self._crashed_world(storage, lease)
            epoch = lease.advance_epoch()
            jnl2 = Journal(storage=storage, epoch=epoch,
                           fence=lease.current_epoch, clock=clock)
            _, _, _, _, _, s2 = make_world(clock=clock, store=store,
                                           backend=backend, bus=bus,
                                           tracer=tracer)
            s2.journal = jnl2
            s2.job_num_chips.journal = jnl2
            s2.ready_jobs.clear()
            s2.done_jobs.clear()
            report = recover_scheduler(s2, fastpath=fastpath)
            results[fastpath] = (
                s2._recovered_tables,
                tuple(sorted((d["job"], d["reason"])
                             for d in report["divergences"])),
                read_state(jnl2).booked,
            )
            s2.stop()
        assert results[False][0] == results[True][0]
        assert results[False][1] == results[True][1]
        assert results[False][2] == results[True][2]

    def test_fastpath_fold_resets_segment(self):
        """A cold fastpath recovery over a big segment folds: the
        recovered journal is snapshot + tiny suffix, and a SECOND
        recovery replays exactly the same state from it."""
        storage = MemoryStorage()
        lease = MemoryLease()
        jnl = Journal(storage=storage, epoch=lease.epoch,
                      fence=lease.current_epoch,
                      compact_bytes=256)  # tiny bound: force the fold
        clock, store, backend, bus, tracer, sched = make_world(journal=jnl)
        submit(sched, store, backend, clock, "f0", epochs=1000)
        clock.advance(3)
        sched.stop()
        epoch = lease.advance_epoch()
        jnl2 = Journal(storage=storage, epoch=epoch,
                       fence=lease.current_epoch, clock=clock,
                       compact_bytes=256)
        _, _, _, _, _, s2 = make_world(journal=jnl2, resume=True,
                                       clock=clock, store=store,
                                       backend=backend, bus=bus,
                                       tracer=tracer)
        snap = jnl2.load_snapshot()
        assert snap is not None and snap["booked"].get("f0", 0) > 0
        tables = s2._recovered_tables
        s2.stop()
        epoch = lease.advance_epoch()
        jnl3 = Journal(storage=storage, epoch=epoch,
                       fence=lease.current_epoch, clock=clock,
                       compact_bytes=256)
        _, _, _, _, _, s3 = make_world(journal=jnl3, resume=True,
                                       clock=clock, store=store,
                                       backend=backend, bus=bus,
                                       tracer=tracer)
        assert s3._recovered_tables == tables
        assert s3._last_recovery_report["divergences"] == []
        s3.stop()

    def test_read_states_parallel_matches_serial(self):
        journals = {}
        for pool in ("a", "b", "c"):
            s = MemoryStorage()
            j = Journal(storage=s)
            for i in range(5):
                j.append("jbook", {"op": "commit",
                                   "job": f"{pool}-{i}", "chips": 1})
            journals[pool] = j
        par = read_states_parallel(journals, workers=3)
        for pool, j in journals.items():
            assert par[pool].booked == read_state(j).booked


# ---- tombstone retention (satellite) ---------------------------------------


class TestRetention:
    def test_snapshot_stops_growing_past_retention(self):
        """The lifetime-growth bound: churn N short-lived jobs through
        a journal with a small retention horizon; after each fold, the
        tombstone map stays bounded by the window, not lifetime."""
        clock = VirtualClock(start=1000.0)
        s = MemoryStorage()
        j = Journal(storage=s, clock=clock,
                    retire_retention_seconds=100.0)
        sizes = []
        for batch in range(6):
            for i in range(20):
                name = f"short-{batch}-{i}"
                j.append("jbook", {"op": "commit", "job": name,
                                   "chips": 1})
                j.append("jretire", {"job": name, "status": "Completed"})
            clock.advance(60.0)
            j.maybe_compact(force=True)
            snap = j.load_snapshot()
            sizes.append(len(snap["retired"]))
        # Two 60 s batches fit the 100 s window: the map holds at most
        # two batches' tombstones and STOPS growing.
        assert sizes[-1] <= 40
        assert sizes[-1] == sizes[-2] == sizes[-3]
        # granted history is pruned with its tombstones.
        snap = j.load_snapshot()
        assert len(snap["granted"]) <= 40

    def test_recent_tombstone_survives_and_prevents_resurrection(self):
        clock = VirtualClock(start=1000.0)
        s = MemoryStorage()
        j = Journal(storage=s, clock=clock,
                    retire_retention_seconds=1e9)
        j.append("jbook", {"op": "commit", "job": "victim", "chips": 2})
        j.append("jretire", {"job": "victim", "status": "Canceled"})
        j.maybe_compact(force=True)
        snap = j.load_snapshot()
        assert snap["retired"]["victim"] == "Canceled"
        assert snap["retired_at"]["victim"] == pytest.approx(1000.0)
        state = read_state(j)
        assert "victim" in state.retired
        assert state.booked == {}

    def test_zero_retention_disables_pruning(self):
        clock = VirtualClock(start=1000.0)
        j = Journal(storage=MemoryStorage(), clock=clock,
                    retire_retention_seconds=0.0)
        j.append("jretire", {"job": "old", "status": "Completed"})
        clock.advance(1e9)
        j.maybe_compact(force=True)
        assert "old" in j.load_snapshot()["retired"]


# ---- trainer-side placement-context CSV (satellite) ------------------------


class TestPlacementContextCsv:
    def test_collector_round_trip(self, tmp_path):
        """EpochCsvLogger writes spread/cotenancy columns; the real-
        mode CsvDirRowSource reads them back into MetricsRow — so
        real-mode learned rows stop defaulting to contiguous."""
        from vodascheduler_tpu.metricscollector.collector import (
            CsvDirRowSource,
        )
        from vodascheduler_tpu.metricscollector.csv_logger import (
            EpochCsvLogger,
        )

        logger = EpochCsvLogger(str(tmp_path), "ctx-job", total_epochs=5)
        logger.log_epoch(epoch_time_sec=10.0, step_time_sec=0.1,
                         workers=4, spread=0.375, cotenancy=0.25)
        logger.log_epoch(epoch_time_sec=9.0, step_time_sec=0.09,
                         workers=4)
        rows = CsvDirRowSource(str(tmp_path)).rows("ctx-job")
        assert rows[0].spread == pytest.approx(0.375)
        assert rows[0].cotenancy == pytest.approx(0.25)
        assert rows[1].spread == 0.0 and rows[1].cotenancy == 0.0
        assert rows[0].step_time_sec == pytest.approx(0.1)

    def test_legacy_csv_without_columns_still_reads(self, tmp_path):
        from vodascheduler_tpu.metricscollector.collector import (
            CsvDirRowSource,
        )

        with open(tmp_path / "old-job.csv", "w") as f:
            f.write("epoch,epoch_time_sec,step_time_sec,workers\n"
                    "0,10.0,0.1,4\n")
        rows = CsvDirRowSource(str(tmp_path)).rows("old-job")
        assert rows[0].spread == 0.0 and rows[0].cotenancy == 0.0

    def test_local_backend_stamps_env(self, tmp_path, monkeypatch):
        """LocalBackend stamps the placement context at spawn: spread 0
        (single host), co-tenancy = other jobs' chips / host chips."""
        from vodascheduler_tpu.cluster.local import LocalBackend

        captured = {}

        def fake_popen(cmd, env=None, **kwargs):
            captured["env"] = env

            class P:
                pid = 4242

                def poll(self):
                    return None

                def kill(self):
                    pass

            return P()

        be = LocalBackend(str(tmp_path), chips=8, hermetic_devices=2)
        monkeypatch.setattr(
            "vodascheduler_tpu.cluster.local.subprocess.Popen",
            fake_popen)
        spec = JobSpec(name="envjob", pool="p",
                       config=JobConfig(min_num_chips=1, max_num_chips=2,
                                        epochs=1))
        be._procs["other"] = type("FakeProc", (),
                                  {"num_chips": 4, "popen": None})()
        be._spawn(spec, 2)
        env = captured["env"]
        assert env["VODA_PLACEMENT_SPREAD"] == "0.0"
        assert float(env["VODA_PLACEMENT_COTENANCY"]) \
            == pytest.approx(0.5)
        be._procs.clear()  # the stub has no real popen to reap
        be.close()


# ---- the crash profile's standby tooth --------------------------------------


class TestModelcheckStandby:
    def test_stale_standby_tooth_caught(self):
        from vodascheduler_tpu.analysis import modelcheck as mc

        result = mc.explore(mc.crash_config(
            variant="stale-standby-serves-decide"))
        assert result.counterexample is not None, \
            "stale-standby-serves-decide must be CAUGHT"
        assert mc.replay_counterexample(result.counterexample), \
            "counterexample must replay deterministically"

    def test_ship_action_in_crash_alphabet(self):
        from vodascheduler_tpu.analysis import modelcheck as mc

        world = mc._make_world(mc.crash_config())
        world.apply("submit:j0")
        assert "ship" in world.enabled()
        world.apply("ship")
        assert world.standby.applier.last_seq > 0
        assert not world._crash_problems


# ---- committed schema-9 artifact pins ---------------------------------------


class TestFailoverArtifactPins:
    def _baseline(self):
        with open(os.path.join(REPO, "doc", "perf_baseline.json")) as f:
            return json.load(f)

    def test_failover_section_pinned(self):
        base = self._baseline()
        assert base["schema"] >= 9
        points = {p["n_jobs"]: p for p in base["failover"]}
        assert 10000 in points
        p10k = points[10000]
        # The acceptance budget: lease-loss -> first committed decide,
        # p95 under one second at 10k jobs.
        assert p10k["takeover_ms"]["p95"] < 1000.0
        # The journaled decide tail holds the PR 8 pin with a live
        # shipping tailer attached.
        assert p10k["decide_with_shipping_ms"]["p95"] < 50.0
        # The recovery-protocol A/B keeps a real win.
        assert p10k["cold_recovery"]["speedup"] >= 1.5
        # Takeovers drained a real suffix (not a no-op handover).
        assert p10k["takeover_suffix_records_mean"] > 0

    def test_recovery_2x_faster_than_pr13_baseline(self):
        """The headline acceptance: the PR 13 committed baseline
        measured the 10k cold recovery at 1.72 s on this machine
        class; the fastpath must keep it >= 2x under that."""
        base = self._baseline()
        points = {p["n_jobs"]: p for p in base["recovery"]}
        assert points[10000]["recovery_seconds"] <= 1.72 / 2.0
        # And the satellite fix: journal_bytes is sampled at the kill
        # point (what recovery must read), never the post-compaction
        # 93-byte artifact again.
        assert points[10000]["journal_bytes"] > 1_000_000

    def test_fleet_recovery_row_pinned(self):
        base = self._baseline()
        rows = {p["total_jobs"]: p for p in base.get("fleet_recovery", [])}
        assert rows, "fleet_recovery section missing from the baseline"
        for n, row in rows.items():
            assert row["recovery_divergences"] == 0
            assert row["recovered_jobs"] > 0
            assert row["parallel_replay_seconds"] \
                <= row["serial_replay_sum_seconds"] * 1.25


# ---- VodaApp standby wiring -------------------------------------------------


@pytest.mark.slow
class TestVodaAppStandby:
    def test_standby_app_takes_over_on_lease_release(self, tmp_path):
        """Two VodaApps on one workdir: the second starts with
        standby=True while the first holds the lease, tails its
        journals, and finishes construction as a WARM takeover the
        moment the leader releases — the production wiring of the
        whole plane (doc/durability.md 'Hot standby')."""
        import threading

        from vodascheduler_tpu.service.app import VodaApp

        workdir = str(tmp_path)
        os.environ.pop("VODA_STANDBY", None)
        leader = VodaApp(workdir=workdir, chips=4, hermetic_devices=None,
                         service_port=0, scheduler_port=0,
                         allocator_port=0)
        spec = JobSpec(name="appjob", pool="default",
                       config=JobConfig(min_num_chips=1, max_num_chips=2,
                                        epochs=1000))
        leader.admission.create_training_job(spec)
        stored = [j.name for j in leader.store.list_jobs()]
        assert stored

        apps = {}

        def run_standby():
            apps["b"] = VodaApp(workdir=workdir, chips=4,
                                hermetic_devices=None,
                                service_port=0, scheduler_port=0,
                                allocator_port=0, standby=True)

        t = threading.Thread(target=run_standby, daemon=True)
        t.start()
        time.sleep(1.5)  # the standby is tailing, leader still leads
        assert "b" not in apps
        leader.stop()  # clean release: expires the lease immediately
        t.join(timeout=60.0)
        assert "b" in apps, "standby never took over"
        b = apps["b"]
        try:
            sched = b.scheduler
            assert sched._last_takeover is not None
            assert sched._last_takeover["epoch"] == b.lease.epoch
            # The admitted job survived the handover.
            assert stored[0] in sched.ready_jobs
            assert b.hot_standby is not None
            assert b.hot_standby.pools["default"].applier.last_seq > 0
        finally:
            b.stop()


# ---- kill -9 failover e2e (satellite) ---------------------------------------


_LEADER = textwrap.dedent("""
    import os, sys, random, threading, time
    sys.path.insert(0, {repo!r})
    from vodascheduler_tpu.allocator import ResourceAllocator
    from vodascheduler_tpu.cluster.fake import (FakeClusterBackend,
                                                WorkloadProfile)
    from vodascheduler_tpu.common.clock import VirtualClock
    from vodascheduler_tpu.common.events import EventBus
    from vodascheduler_tpu.common.job import JobConfig, JobSpec, TrainingJob
    from vodascheduler_tpu.common.store import FileJobStore
    from vodascheduler_tpu.durability.journal import Journal
    from vodascheduler_tpu.durability.leader import FileLease
    from vodascheduler_tpu.obs import tracer as obs_tracer
    from vodascheduler_tpu.placement import PlacementManager
    from vodascheduler_tpu.scheduler import Scheduler

    workdir = {workdir!r}
    ttl = {ttl!r}
    clock = VirtualClock(start=1000.0)
    tracer = obs_tracer.Tracer(clock=clock, ring_size=64)
    store = FileJobStore(os.path.join(workdir, "state.json"))
    bus = EventBus()
    backend = FakeClusterBackend(clock, restart_overhead_seconds=2.0)
    for i in range(4):
        backend.add_host(f"host-{{i}}", 4, announce=False)
    lease = FileLease(os.path.join(workdir, "lease"), holder="leader",
                      ttl_seconds=ttl)
    lease.try_acquire()

    def renew():
        while True:
            lease.renew()
            time.sleep(ttl / 5.0)

    threading.Thread(target=renew, daemon=True).start()
    jnl = Journal(path=os.path.join(workdir, "pool.wal"), clock=clock,
                  epoch=lease.epoch, fence=lease.current_epoch)
    sched = Scheduler("p", backend, store, ResourceAllocator(store),
                      clock, bus=bus,
                      placement_manager=PlacementManager("p"),
                      rate_limit_seconds=1.0, profile_cpu=False,
                      tracer=tracer, journal=jnl)
    rng = random.Random(11)
    i = 0
    while True:  # event storm until killed
        name = f"storm-{{i:04d}}"
        spec = JobSpec(name=name, pool="p",
                       config=JobConfig(min_num_chips=1,
                                        max_num_chips=rng.choice((1, 2, 4)),
                                        epochs=3))
        backend.register_profile(
            name, WorkloadProfile(epoch_seconds_at_1=8.0))
        store.insert_job(TrainingJob.from_spec(spec,
                                               submit_time=clock.now()))
        sched.create_training_job(name)
        if rng.random() < 0.3 and sched.ready_jobs:
            sched.delete_training_job(
                rng.choice(sorted(sched.ready_jobs)))
        clock.advance(rng.choice((0.2, 1.5, 3.0)))
        i += 1
        if i == 5:
            print("STORMING", flush=True)
""")


@pytest.mark.slow
class TestKillNineFailoverE2E:
    def test_kill9_leader_standby_takes_over_within_budget(self, tmp_path):
        """kill -9 the leader mid-event-storm with a LIVE standby
        attached via shipping; the standby must take over within one
        lease TTL + the takeover budget, and the recovered state must
        equal the journal's committed prefix: no lost admitted jobs,
        no double-booked chips."""
        workdir = str(tmp_path)
        ttl = 3.0
        leader = subprocess.Popen(
            [sys.executable, "-c",
             _LEADER.format(repo=REPO, workdir=workdir, ttl=ttl)],
            stdout=subprocess.PIPE, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert leader.stdout.readline().strip() == "STORMING"

        # The live standby: tail the leader's journal while it storms.
        wal = os.path.join(workdir, "pool.wal")
        standby = PoolStandby("p", FileTailSource(wal))
        deadline = time.monotonic() + 10.0
        while standby.applier.last_seq == 0 \
                and time.monotonic() < deadline:
            standby.poll()
            time.sleep(0.02)
        assert standby.applier.last_seq > 0
        time.sleep(0.5)
        standby.poll()
        os.kill(leader.pid, signal.SIGKILL)
        t_killed = time.monotonic()
        leader.wait(timeout=30)

        # Poll shipping + the lease exactly like HotStandby would.
        from vodascheduler_tpu.common.store import FileJobStore
        from vodascheduler_tpu.durability.leader import LeaseHeld

        holder = FileLease(os.path.join(workdir, "lease"),
                           holder="standby", ttl_seconds=ttl)
        epoch = None
        while time.monotonic() < t_killed + 2 * ttl + 5.0:
            standby.poll()
            try:
                epoch = holder.try_acquire()
                break
            except LeaseHeld:
                time.sleep(0.05)
        assert epoch is not None, "lease never expired"
        t_acquired = time.monotonic()
        assert t_acquired - t_killed <= 2 * ttl  # within one TTL of expiry

        # The committed prefix, parsed INDEPENDENTLY of the takeover.
        clock = VirtualClock(start=2000.0)
        expected = read_state(Journal(path=wal, clock=clock, epoch=epoch))

        bundle = standby.prepare_takeover()
        jnl2 = Journal(path=wal, epoch=epoch,
                       fence=holder.current_epoch, clock=clock,
                       resume_hint=bundle["resume_hint"])
        store = FileJobStore(os.path.join(workdir, "state.json"))
        # Fresh backend: the fake cluster died with the leader, so
        # every journal-RUNNING job must reconcile to backend_lost.
        _, _, backend, bus, tracer, sched = make_world(
            journal=jnl2, clock=clock, store=store, hosts=4,
            resume=True, recovered_state=bundle["state"])
        rec = finish_takeover(sched, standby, t_acquired, epoch,
                              bundle["suffix_records"])
        assert rec["duration_ms"] < 5000.0  # budget: takeover work, bounded

        booked_t, ready_t, done_t, _ = sched._recovered_tables
        booked, ready, done = dict(booked_t), dict(ready_t), dict(done_t)
        # The standby state == the journal's committed prefix.
        for name, status in expected.statuses.items():
            assert name in ready, f"lost journaled job {name}"
            assert ready[name] == "Waiting"
            assert booked.get(name, 0) == 0
        for name in expected.retired:
            assert name not in ready and name in done
        # No lost admitted jobs: every store job the journal never saw
        # is re-accepted.
        for job in store.list_jobs(pool="p"):
            if job.name in expected.retired:
                continue
            assert job.name in ready, f"lost admitted job {job.name}"
        # No double-booked chips (trivially: the dead backend freed all).
        assert sum(booked.values()) == 0
        with backend._state_lock:
            per_host = {}
            for n, sim in backend.jobs.items():
                for h, c in sim.placements:
                    per_host[h] = per_host.get(h, 0) + c
        hosts = backend.list_hosts()
        for h, used in per_host.items():
            assert used <= hosts[h], f"double-booked {h}"
        sched.stop()
