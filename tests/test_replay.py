"""Trace replay harness tests."""

import json
import subprocess
import sys

import pytest

from vodascheduler_tpu.placement import PoolTopology
from vodascheduler_tpu.replay import (
    ReplayHarness,
    load_trace,
    philly_like_trace,
    save_trace,
)
from vodascheduler_tpu.replay.simulator import PreemptionEvent


def small_topology():
    return PoolTopology(torus_dims=(4, 2, 2), host_block=(2, 2, 1))  # 16 chips


class TestTrace:
    def test_deterministic(self):
        a = philly_like_trace(num_jobs=16, seed=7)
        b = philly_like_trace(num_jobs=16, seed=7)
        assert a == b
        c = philly_like_trace(num_jobs=16, seed=8)
        assert a != c

    def test_roundtrip(self, tmp_path):
        trace = philly_like_trace(num_jobs=8)
        path = str(tmp_path / "trace.json")
        save_trace(trace, path)
        assert load_trace(path) == trace

    def test_shape(self):
        trace = philly_like_trace(num_jobs=64)
        assert len(trace) == 64
        assert all(t.min_chips <= t.max_chips for t in trace)
        assert all(t.epochs >= 1 for t in trace)
        # arrivals strictly ordered
        offsets = [t.submit_offset_seconds for t in trace]
        assert offsets == sorted(offsets)


class TestReplay:
    def test_all_jobs_complete(self):
        trace = philly_like_trace(num_jobs=12, seed=3)
        h = ReplayHarness(trace, algorithm="ElasticFIFO",
                          topology=small_topology())
        report = h.run()
        assert report.completed == 12
        assert report.failed == 0
        assert 0.0 < report.chip_utilization <= 1.0
        assert report.avg_jct_seconds > 0

    def test_elastic_beats_nonelastic_on_util(self):
        trace = philly_like_trace(num_jobs=24, seed=5)
        elastic = ReplayHarness(trace, algorithm="ElasticFIFO",
                                topology=small_topology()).run()
        rigid = ReplayHarness(trace, algorithm="FIFO",
                              topology=small_topology()).run()
        assert elastic.chip_utilization > rigid.chip_utilization

    def test_failures_counted(self):
        trace = philly_like_trace(num_jobs=10, seed=11, failure_fraction=0.5)
        h = ReplayHarness(trace, algorithm="ElasticFIFO",
                          topology=small_topology())
        report = h.run()
        assert report.failed > 0
        assert report.completed + report.failed == 10

    def test_spot_preemption_survives(self):
        trace = philly_like_trace(num_jobs=8, seed=13)
        topo = small_topology()
        # rip out two hosts mid-trace, return one later
        names = [topo.host_name(c) for c in topo.host_coords()]
        ev = [PreemptionEvent(at_seconds=1800.0, host=names[0]),
              PreemptionEvent(at_seconds=2400.0, host=names[1]),
              PreemptionEvent(at_seconds=7200.0, host=names[0], add=True,
                              chips=topo.chips_per_host)]
        h = ReplayHarness(trace, algorithm="ElasticTiresias",
                          topology=topo, preemptions=ev)
        report = h.run()
        assert report.completed == 8


@pytest.mark.slow
class TestBenchScript:
    def test_bench_prints_json_line(self):
        import os
        env = dict(os.environ, VODA_BENCH_HW="0")  # replay only: hermetic
        out = subprocess.run([sys.executable, "bench.py"], capture_output=True,
                             text=True, timeout=900, cwd="/root/repo",
                             env=env)
        assert out.returncode == 0, out.stderr
        line = out.stdout.strip().splitlines()[-1]
        data = json.loads(line)
        assert set(data) >= {"metric", "value", "unit", "vs_baseline"}
        assert data["value"] > 0.5  # sanity: util should be well over 50%


def test_bench_scenario_meets_targets():
    """Regression guard for the headline bench (bench.py): the r7 knee
    knobs (rate 20s / hysteresis 2.0 / cooldown 300s, config.py) with
    the headline spot-preemption schedule must clear BOTH halves of the
    BASELINE metric. Guard values are measurements under the
    PLACEMENT-SENSITIVE STEP-TIME MODEL (doc/placement.md) on top of
    critical-path actuation pricing and two-tier resize pricing: every
    job's speedup is degraded by its collective traffic x host-set
    spread (comms_fraction x topology.spread on the exponent), so the
    same schedule now carries its modeled ICI cost — ~10.6% of fleet
    throughput on this trace — and the headline moved from the
    spread-blind 0.8709 / 10,133 s to the honest 0.8700 / 10,749.8 s. A
    cost-model correction, not a regression, exactly like the r7
    actuation-pricing move before it (0.8673/8,602 s zero-cost passes;
    0.8715/8,694 s cold-only pricing are likewise not comparable).
    Sweep provenance: scripts/replay_sweep.py,
    doc/replay_sweep_r7.json.

    PR 12 (doc/fractional-sharing.md) added co-tenant interference to
    the step-time model: co-resident jobs now pay their family's
    interference fraction x cotenancy every step (~1.4% of fleet
    throughput on this trace), and fractional tenants are placed with
    the interference price (0.8628 ss-util / 10,523.8 s avg JCT at
    that point).

    The learned-model plane (doc/learned-models.md) is ON by default:
    the collector fits each job's measured scaling and the allocator's
    gain lookups read the fitted curve instead of the linear prior at
    unmeasured counts. On this trace — whose families MATCH their
    comms priors, so only the speedup refinement binds — the policy
    stops granting marginal chips to sublinearly-scaling jobs, and
    drift episodes (6 on this trace) re-plan onto refreshed curves:
    avg JCT improved to 10,478.7 s, restarts dropped to 144, at
    ~0.1 points of raw occupancy (ss-util 0.8617 — chips idling
    instead of earning no speedup). A policy improvement judged by
    the BASELINE metric's JCT half, honestly re-pinned on both
    halves."""
    _, h = _headline_harness(64, (4, 4, 4))
    r = h.run()
    assert r.completed == 64
    assert r.failed == 0, r                       # preemption kills no job
    assert r.steady_state_utilization >= 0.855, r  # measured 0.8617
    assert r.avg_jct_seconds <= 11_000.0, r       # measured 10,478.7 s
    assert r.p95_jct_seconds <= 21_700.0, r       # measured 21,533.9 s
    assert r.steady_state_seconds > 0.5 * r.makespan_seconds, r
    assert r.restarts_total <= 175, r             # measured 144
    # The occupancy half of the learned-curve trade shows up here:
    # chips the fitted curves say earn no speedup now idle instead of
    # being granted (measured 0.8514, was 0.8617 prior-only), while
    # ss-util, JCT, p95, and restarts all improved above.
    assert r.attainable_utilization >= 0.85, r    # measured 0.8514
    # The placement-sensitive model is actually pricing something:
    # the headline's placements lose a nonzero, bounded share of
    # modeled throughput to ICI spread (measured 0.1083).
    assert 0.0 < r.comms_penalty_mean < 0.25, r
    # ... and the interference model prices co-tenancy without letting
    # it dominate (measured 0.0138).
    assert 0.0 < r.interference_penalty_mean < 0.10, r
    # The resize-path mix must show the fast path actually firing: the
    # Philly mode is small (single-host) jobs, whose resizes stay on
    # their host and reshard in place.
    assert r.resizes_inplace_total > 0, r
    # The actuation plane's headline claim: the pass's priced cost is
    # the per-wave critical path, strictly cheaper than the serial sum
    # the pre-wave engine paid (measured 4,412 vs 5,367 s).
    assert 0 < r.actuation_critical_path_seconds \
        < r.actuation_serial_sum_seconds, r


def test_topology_mix_comms_aware_beats_count_only():
    """The tentpole's proof row (doc/placement.md "Proof", attached to
    the bench artifact as detail.placement_comms): on the bimodal
    topology-sensitive mix — long-lived small fillers fragmenting the
    torus under wide elastic comms-heavy jobs, defragmentation on in
    both arms — the comms-aware placement objective must beat the
    count-only baseline (VODA_PLACEMENT_COMMS=0 semantics) on BOTH
    modeled step time (busy-weighted comms penalty) and avg JCT, under
    the SAME placement-sensitive physics. Measured at the pinned seed:
    aware 5,874.2 s / penalty 0.1146 vs count-only 6,074.1 s / 0.1482
    (3.3% JCT win, 23% less throughput lost to spread)."""
    from vodascheduler_tpu.replay.compare import placement_comms_ab

    rows = placement_comms_ab()
    aware, count = rows["aware"], rows["count_only"]
    assert aware["completed"] == count["completed"] == 48
    assert aware["failed"] == count["failed"] == 0
    assert aware["comms_penalty_mean"] < count["comms_penalty_mean"], rows
    assert aware["avg_jct_s"] < count["avg_jct_s"], rows
    assert rows["win"]["jct_ratio"] < 1.0, rows
    assert rows["win"]["penalty_delta"] > 0.0, rows


def test_fractional_sharing_recovers_stranded_capacity():
    """The PR 12 tentpole's proof row (doc/fractional-sharing.md
    "Proof", attached to the bench artifact as
    detail.fractional_sharing): on the bimodal topology mix — whose
    filler class (1-2 chip resnet50 jobs) is exactly the sub-host
    eval/debug/fine-tune long tail — fractional sub-host sharing must
    recover at least 3 raw-utilization points over the whole-host-
    minimum baseline (each exclusive filler strands 2-3 of its host's
    4 chips) WITHOUT making large jobs (>= 8 max chips) more than 2%
    slower, under the same interference-sensitive physics in both
    arms. Measured at the pinned seed: sharing 0.7297 raw util /
    11,626.2 s large JCT vs baseline 0.6692 / 14,317.0 (+6.05 points;
    large jobs 19% FASTER — exclusive fillers were crowding them out),
    with the sharing arm's interference price nonzero (0.0031) — the
    win is measured against honest physics, not free co-tenancy."""
    from vodascheduler_tpu.replay.compare import fractional_sharing_ab

    rows = fractional_sharing_ab()
    sharing, base = rows["sharing"], rows["whole_host"]
    assert sharing["completed"] == base["completed"] == 48
    assert sharing["failed"] == base["failed"] == 0
    assert rows["win"]["raw_util_delta"] >= 0.03, rows
    assert rows["win"]["large_jct_ratio"] <= 1.02, rows
    # The sharing arm actually co-tenants (and pays for it): a zero
    # interference price would mean the A/B compared nothing.
    assert sharing["interference_penalty_mean"] > 0.0, rows
    assert base["interference_penalty_mean"] == 0.0, rows


def test_learned_models_beat_prior_only():
    """The learned-models tentpole's proof row (doc/learned-models.md
    "Proof", attached to the bench artifact as detail.learned_models):
    on the mismatched-prior mix — heavies whose true comms share
    (0.5) and scaling exponent (0.65) are far from the family tables'
    0.18-0.25 and the allocator's linear prior, fillers whose real
    co-tenant interference (0.35) is 4x the table — online-learned
    scheduling (VODA_LEARNED_MODELS=1, the default) must beat the
    prior-only baseline on avg JCT AND on the total modeled
    placement/interference penalty, under the SAME physics. Measured
    at the pinned seed: learned 10,610.3 s avg JCT / 0.8584 ss-util
    vs prior-only 10,879.9 s / 0.8289 (2.5% JCT win, +3 util
    points, 3.2 points less modeled throughput lost)."""
    from vodascheduler_tpu.replay.compare import learned_models_ab

    rows = learned_models_ab()
    learned, prior = rows["learned"], rows["prior_only"]
    assert learned["completed"] == prior["completed"] == 48
    assert learned["failed"] == prior["failed"] == 0
    assert learned["avg_jct_s"] < prior["avg_jct_s"], rows
    assert rows["win"]["jct_ratio"] < 1.0, rows
    assert rows["win"]["penalty_delta"] > 0.0, rows
    # The prior-only arm is genuinely prior-only: no drift rescheds.
    assert prior["drift_rescheds"] == 0, rows


def _headline_harness(num_jobs: int, torus_dims: tuple,
                      algorithm: str = "ElasticTiresias",
                      failure_fraction: float = 0.0):
    """The bench.py headline configuration (explicitly pinned knee knobs
    + config-5 spot dip) at a given scale — ONE definition shared by
    every guard in this file so a future knee re-tune moves them all."""
    from vodascheduler_tpu.placement import PoolTopology
    from vodascheduler_tpu.replay import ReplayHarness, philly_like_trace
    from vodascheduler_tpu.replay.simulator import config5_preemptions

    from vodascheduler_tpu import config

    trace = philly_like_trace(num_jobs=num_jobs, seed=20260729,
                              max_job_chips=64,
                              failure_fraction=failure_fraction)
    topo = PoolTopology(torus_dims=torus_dims, host_block=(2, 2, 1))
    return trace, ReplayHarness(
        trace, algorithm=algorithm, topology=topo,
        rate_limit_seconds=config.RATE_LIMIT_SECONDS,
        scale_out_hysteresis=config.SCALE_OUT_HYSTERESIS,
        resize_cooldown_seconds=config.RESIZE_COOLDOWN_SECONDS,
        preemptions=config5_preemptions(topo))


def test_v5p128_scale_replay():
    """BASELINE config 5 names v5p-128: double the pool and the job
    count (+ the spot dip) and the whole control plane must still clear
    the north-star bars. Simulated time — runs in under a second.
    Interference-sensitive measurements (r7 knobs + comms cost model +
    PR 12's co-tenant interference, doc/fractional-sharing.md): util
    0.8490 / avg 9,508.4 s / p95 21,447.5 s with 1.23% of throughput
    priced to co-tenancy — a cost-model correction over the
    interference-blind 0.8575 / 9,030.2 / 20,253.4 (which in turn
    corrected the spread-blind 0.8505 / 8,165.7 / 18,664.8): the dense
    128-job mix co-locates its small-job tail heavily, and that
    sharing now carries its modeled price. The steady-state window
    is ~30% of makespan at this scale (the heavy tail drains long
    after arrivals stop), so no ss_frac assertion here — the 64-job
    guard carries it. The learned-model plane (doc/learned-models.md,
    default-on) improved every axis at this scale: 0.8515 ss-util /
    9,103.9 s avg / 20,924.1 s p95 (was 0.8490 / 9,508.4 / 21,447.5
    prior-only) — the dense mix has more repeat submissions, so
    category-inherited fitted curves pay off sooner."""
    _, h = _headline_harness(128, (4, 4, 8))
    r = h.run()
    assert r.completed == 128
    assert r.failed == 0, r
    assert r.steady_state_utilization >= 0.84, r  # measured 0.8515
    assert r.avg_jct_seconds <= 9_500.0, r        # measured 9,103.9 s
    assert r.p95_jct_seconds <= 21_500.0, r       # measured 20,924.1 s


def test_algorithm_compare_runs_all_registered():
    """The per-algorithm comparison harness (replay/compare.py) replays
    the same trace under every registered algorithm and reports
    completed == num_jobs for the two families it samples here (full
    8-way runs live in doc/benchmarks.md; this keeps the module wired)."""
    from vodascheduler_tpu.replay.compare import as_rows, compare_algorithms

    reports = compare_algorithms(num_jobs=8, seed=7,
                                 algorithms=("FIFO", "ElasticTiresias"))
    rows = as_rows(reports)
    assert [r["algorithm"] for r in rows] == ["FIFO", "ElasticTiresias"]
    assert all(r["completed"] == 8 and r["failed"] == 0 for r in rows)
    assert all(r["avg_jct_s"] > 0 for r in rows)


@pytest.mark.slow
def test_failure_matrix_exact_accounting_all_algorithms():
    """20% injected crashes + the spot dip, replayed under every
    registered algorithm at the headline configuration: each must
    account exactly (completed + failed == num_jobs, failed == the
    injected count) — a lost or double-counted job under ANY policy is
    a control-plane bug, not a policy difference. Full table in
    doc/benchmarks.md."""
    from vodascheduler_tpu.algorithms import ALGORITHM_NAMES

    for algo in ALGORITHM_NAMES:
        trace, h = _headline_harness(64, (4, 4, 4), algorithm=algo,
                                     failure_fraction=0.2)
        injected = sum(1 for t in trace if t.fail_at_epoch is not None)
        assert injected > 0
        r = h.run()
        assert r.completed == 64 - injected, (algo, r)
        assert r.failed == injected, (algo, r)


def test_shipped_knobs_match_sweep_artifact():
    """config.py's resize knobs are documented as the pick of the
    checked-in sweep (doc/replay_sweep_r7.json panel_knobs) — pin that
    so a re-sweep that forgets to update config (or vice versa) fails
    fast instead of shipping knobs the evidence doesn't describe."""
    import os

    from vodascheduler_tpu import config

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "doc", "replay_sweep_r7.json")
    with open(path) as f:
        knobs = json.load(f)["panel_knobs"]
    assert config.RATE_LIMIT_SECONDS == knobs["rate"]
    assert config.SCALE_OUT_HYSTERESIS == knobs["hyst"]
    assert config.RESIZE_COOLDOWN_SECONDS == knobs["cooldown"]
