"""Trace replay harness tests."""

import json
import subprocess
import sys

import pytest

from vodascheduler_tpu.placement import PoolTopology
from vodascheduler_tpu.replay import (
    ReplayHarness,
    load_trace,
    philly_like_trace,
    save_trace,
)
from vodascheduler_tpu.replay.simulator import PreemptionEvent


def small_topology():
    return PoolTopology(torus_dims=(4, 2, 2), host_block=(2, 2, 1))  # 16 chips


class TestTrace:
    def test_deterministic(self):
        a = philly_like_trace(num_jobs=16, seed=7)
        b = philly_like_trace(num_jobs=16, seed=7)
        assert a == b
        c = philly_like_trace(num_jobs=16, seed=8)
        assert a != c

    def test_roundtrip(self, tmp_path):
        trace = philly_like_trace(num_jobs=8)
        path = str(tmp_path / "trace.json")
        save_trace(trace, path)
        assert load_trace(path) == trace

    def test_shape(self):
        trace = philly_like_trace(num_jobs=64)
        assert len(trace) == 64
        assert all(t.min_chips <= t.max_chips for t in trace)
        assert all(t.epochs >= 1 for t in trace)
        # arrivals strictly ordered
        offsets = [t.submit_offset_seconds for t in trace]
        assert offsets == sorted(offsets)


class TestReplay:
    def test_all_jobs_complete(self):
        trace = philly_like_trace(num_jobs=12, seed=3)
        h = ReplayHarness(trace, algorithm="ElasticFIFO",
                          topology=small_topology())
        report = h.run()
        assert report.completed == 12
        assert report.failed == 0
        assert 0.0 < report.chip_utilization <= 1.0
        assert report.avg_jct_seconds > 0

    def test_elastic_beats_nonelastic_on_util(self):
        trace = philly_like_trace(num_jobs=24, seed=5)
        elastic = ReplayHarness(trace, algorithm="ElasticFIFO",
                                topology=small_topology()).run()
        rigid = ReplayHarness(trace, algorithm="FIFO",
                              topology=small_topology()).run()
        assert elastic.chip_utilization > rigid.chip_utilization

    def test_failures_counted(self):
        trace = philly_like_trace(num_jobs=10, seed=11, failure_fraction=0.5)
        h = ReplayHarness(trace, algorithm="ElasticFIFO",
                          topology=small_topology())
        report = h.run()
        assert report.failed > 0
        assert report.completed + report.failed == 10

    def test_spot_preemption_survives(self):
        trace = philly_like_trace(num_jobs=8, seed=13)
        topo = small_topology()
        # rip out two hosts mid-trace, return one later
        names = [topo.host_name(c) for c in topo.host_coords()]
        ev = [PreemptionEvent(at_seconds=1800.0, host=names[0]),
              PreemptionEvent(at_seconds=2400.0, host=names[1]),
              PreemptionEvent(at_seconds=7200.0, host=names[0], add=True,
                              chips=topo.chips_per_host)]
        h = ReplayHarness(trace, algorithm="ElasticTiresias",
                          topology=topo, preemptions=ev)
        report = h.run()
        assert report.completed == 8


@pytest.mark.slow
class TestBenchScript:
    def test_bench_prints_json_line(self):
        out = subprocess.run([sys.executable, "bench.py"], capture_output=True,
                             text=True, timeout=300, cwd="/root/repo")
        assert out.returncode == 0, out.stderr
        line = out.stdout.strip().splitlines()[-1]
        data = json.loads(line)
        assert set(data) >= {"metric", "value", "unit", "vs_baseline"}
        assert data["value"] > 0.5  # sanity: util should be well over 50%


def test_bench_scenario_meets_targets():
    """Regression guard for the headline bench (bench.py): steady-state
    utilization >= 0.9 and restart burn bounded on the 64-job Philly
    replay (VERDICT r1 item 4: raw >= 0.85 in a demand-saturated window,
    restarts < ~200)."""
    from vodascheduler_tpu.placement import PoolTopology
    from vodascheduler_tpu.replay import ReplayHarness, philly_like_trace

    trace = philly_like_trace(num_jobs=64, seed=20260729)
    topo = PoolTopology(torus_dims=(4, 4, 4), host_block=(2, 2, 1))
    h = ReplayHarness(trace, algorithm="ElasticTiresias", topology=topo,
                      rate_limit_seconds=45.0)
    r = h.run()
    assert r.completed == 64
    assert r.steady_state_utilization >= 0.90, r
    assert r.steady_state_seconds > 0.5 * r.makespan_seconds, r
    assert r.restarts_total <= 220, r
    # Feasibility enforcement held throughout: every job's final grant in
    # the simulated backend history was a feasible count (spot-check via
    # the placement topology's own predicate on the report totals).
    assert r.attainable_utilization >= 0.90, r
