"""Thread-stress for the control plane's locking discipline.

SURVEY.md §5.2: the reference serializes with two RW mutexes and a
single-consumer channel, and ships no concurrency test at all; r3's
VERDICT flagged the same gap here. This test hammers one scheduler from
five concurrent threads — submissions, clock advances (firing backend
completion timers), host churn, live algorithm/ratelimit flips, and
status readers — then proves the system stayed coherent: no thread
raised, no deadlock, every job terminal or cleanly allocated within
capacity and its own bounds.
"""

import json
import os
import threading
import time

import pytest

from vodascheduler_tpu.allocator import ResourceAllocator
from vodascheduler_tpu.cluster.fake import FakeClusterBackend, WorkloadProfile
from vodascheduler_tpu.common.clock import VirtualClock
from vodascheduler_tpu.common.events import EventBus
from vodascheduler_tpu.common.job import JobConfig, JobSpec
from vodascheduler_tpu.common.store import JobStore
from vodascheduler_tpu.placement import PlacementManager, PoolTopology
from vodascheduler_tpu.scheduler import Scheduler
from vodascheduler_tpu.service import AdmissionService

# Hard cap on the whole storm; the actual run stops STORM_TAIL_SECONDS
# after the submitter finishes (~0.1 s), so the fast suite pays a few
# seconds, not the cap.
WALL_BUDGET_SECONDS = 12.0
STORM_TAIL_SECONDS = 3.0
NUM_JOBS = 36


def _build():
    clock = VirtualClock(start=1_700_000_000.0)
    store = JobStore()
    bus = EventBus()
    # A small real actuation latency + forced-parallel waves: the storm
    # exercises the decide/actuate lock split (workers booking while
    # readers/advancers/chaos hammer the lock), not just the old
    # everything-under-one-lock path.
    backend = FakeClusterBackend(clock, restart_overhead_seconds=5.0,
                                 actuation_latency_seconds=0.005)
    topology = PoolTopology(torus_dims=(4, 2, 2), host_block=(2, 2, 1))
    pm = PlacementManager("stress", topology=topology)
    pm.add_hosts_from_topology(topology)
    for coord in topology.host_coords():
        backend.add_host(topology.host_name(coord),
                         topology.chips_per_host, announce=False)
    sched = Scheduler("stress", backend, store,
                      ResourceAllocator(store), clock, bus=bus,
                      placement_manager=pm, algorithm="ElasticTiresias",
                      rate_limit_seconds=5.0, actuation_parallel=True)
    admission = AdmissionService(store, bus, clock)
    # Fleet coordinator over the pool (doc/observability.md "Fleet
    # decide"): the storm drives pump/fleet_stats through it so the
    # witness records the fleet lock's (leaf) behavior and the pinned
    # lock_order.json regenerates with the fleet node.
    from vodascheduler_tpu.scheduler.fleet import FleetCoordinator
    fleet = FleetCoordinator({"stress": sched}, workers=2)
    return clock, store, backend, sched, admission, topology, fleet


LOCK_ORDER_PINNED = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "doc", "lock_order.json")
THREAD_ROLES_PINNED = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "doc", "thread_roles.json")


def test_scheduler_survives_concurrent_hammering(lock_witness):
    clock, store, backend, sched, admission, topology, fleet = _build()
    # Runtime half of the invariant-enforcement plane
    # (doc/static-analysis.md): witness the storm's actual lock
    # acquisitions. Any order cycle, any backend mutator entered with a
    # table lock held, or any lock nesting NOT in the pinned
    # doc/lock_order.json artifact fails the test.
    lock_witness.instrument(sched, "_lock", "scheduler._lock")
    lock_witness.instrument(backend, "_state_lock",
                            "fake_backend._state_lock")
    lock_witness.instrument(clock, "_lock", "virtual_clock._lock")
    lock_witness.instrument(fleet, "_lock", "fleet._lock")
    lock_witness.guard_backend(backend, "fake_backend")
    # Access witness (doc/static-analysis.md "Race witness"): every
    # private-attribute touch on the scheduler and fleet coordinator is
    # recorded as (thread role, class, attr, kind, lock-held?) and must
    # be a subset of the statically-pinned doc/thread_roles.json
    # ownership map. Shares the lock witness's TLS stack so "guarded"
    # means the owner's instrumented lock really was held.
    from vodascheduler_tpu.analysis import RaceWitness
    race_witness = RaceWitness(locks_held_fn=lock_witness.held)
    race_witness.watch(sched, cls_name="Scheduler",
                       guard_locks=("scheduler._lock",))
    race_witness.watch(fleet, cls_name="FleetCoordinator",
                       guard_locks=("fleet._lock",))
    errors = []
    stop = threading.Event()
    submitted = []
    submitted_lock = threading.Lock()

    def guard(fn):
        def run():
            try:
                fn()
            except Exception as e:  # noqa: BLE001 - collected and asserted
                errors.append(e)
                stop.set()
        return run

    def submitter():
        for i in range(NUM_JOBS):
            if stop.is_set():
                return
            spec = JobSpec(
                name=f"stress-{i}", pool="stress", model="synthetic",
                config=JobConfig(min_num_chips=1 + i % 2,
                                 max_num_chips=2 + i % 6,
                                 epochs=2 + i % 3))
            name = admission.create_training_job(spec)
            backend.register_profile(name, WorkloadProfile(
                epoch_seconds_at_1=60.0 + 10 * (i % 5),
                speedup_exponent=0.9))
            with submitted_lock:
                submitted.append(name)
            time.sleep(0.002)

    def advancer():
        # The only thread that advances virtual time (VirtualClock fires
        # timers inline); small steps keep the interleaving hot.
        while not stop.is_set():
            clock.advance(7.0)
            time.sleep(0.001)

    def chaos():
        names = [topology.host_name(c) for c in topology.host_coords()]
        flip = 0
        while not stop.is_set():
            host = names[flip % len(names)]
            backend.remove_host(host)
            time.sleep(0.004)
            backend.add_host(host, topology.chips_per_host)
            sched.set_algorithm(("ElasticFIFO", "ElasticTiresias",
                                 "ElasticSRJF")[flip % 3])
            sched.set_rate_limit(3.0 + flip % 5)
            flip += 1
            time.sleep(0.004)

    def reader():
        # REST-shaped traffic: the snapshot cache and the lock-free
        # fleet view, exactly what scrapes and dashboards hit.
        while not stop.is_set():
            table = sched.status_table()
            for row in table:
                assert row["chips"] >= 0
            snap = fleet.fleet_snapshot()
            assert snap["totals"]["pools"] == 1
            time.sleep(0.001)

    def pumper():
        # Decide-shaped traffic: pump through the fleet coordinator (the
        # production driver) so the witness records the fleet lock's
        # (leaf) behavior, then the scheduler's own pending-pass pump.
        while not stop.is_set():
            fleet.run_pending()
            sched.pump()
            sched.update_time_metrics()
            time.sleep(0.001)

    # Role-prefixed names (vodarace.ROLE_PREFIXES): each storm thread
    # impersonates the production role whose entry points it drives, so
    # the access witness checks its touches against that role's pinned
    # ownership row — an unnamed thread would be "main" and invisible.
    roles = {submitter: "voda-rest-submitter",
             advancer: "voda-timer-advancer",
             chaos: "voda-rest-chaos",
             reader: "voda-rest-reader",
             pumper: "voda-scheduler-daemon-pump"}
    threads = [threading.Thread(target=guard(fn), daemon=True,
                                name=roles[fn])
               for fn in (submitter, advancer, chaos, reader, pumper)]
    deadline = time.monotonic() + WALL_BUDGET_SECONDS
    for t in threads:
        t.start()
    # Let the submitter finish, then keep the storm going briefly.
    threads[0].join(timeout=WALL_BUDGET_SECONDS)
    tail_until = min(deadline, time.monotonic() + STORM_TAIL_SECONDS)
    while time.monotonic() < tail_until and not stop.is_set():
        time.sleep(0.05)
    stop.set()
    for t in threads[1:]:
        t.join(timeout=10.0)
        assert not t.is_alive(), "worker thread failed to stop: deadlock?"
    assert not errors, errors

    # The lock must be free (deadlock detector) and the scheduler still
    # responsive after the storm.
    assert sched._lock.acquire(timeout=5.0), "scheduler lock leaked"
    sched._lock.release()
    sched.trigger_resched()
    sched.pump()

    # Drain: advance simulated time until every submitted job reaches a
    # terminal state (completions ride backend timers).
    with submitted_lock:
        names = list(submitted)
    assert len(names) == NUM_JOBS
    for _ in range(5_000):
        done = set(backend.completed) | set(backend.failed)
        if all(n in done for n in names):
            break
        sched.pump()
        clock.advance(30.0)
    done = set(backend.completed) | set(backend.failed)
    assert all(n in done for n in names), (
        f"{len(done & set(names))}/{len(names)} terminal")

    # Post-quiesce coherence: allocations empty or within bounds.
    for name, chips in sched.job_num_chips.items():
        job = store.get_job(name)
        assert job is not None
        assert chips == 0 or (job.config.min_num_chips <= chips
                              <= job.config.max_num_chips)

    # Lock-order witness verdict. VODA_LOCKWITNESS_WRITE=1 regenerates
    # the pinned artifact (`make lock-order`); otherwise the witnessed
    # graph must be a subset of what a reviewer already signed off on.
    assert lock_witness.problems() == []
    assert lock_witness.edges(), "storm should witness real lock nestings"
    if os.environ.get("VODA_LOCKWITNESS_WRITE"):
        lock_witness.dump(LOCK_ORDER_PINNED)
    with open(LOCK_ORDER_PINNED) as f:
        pinned = json.load(f)
    new_edges = lock_witness.new_edges_vs(pinned)
    assert not new_edges, (
        f"unreviewed lock nesting(s) {new_edges}: update "
        f"doc/lock_order.json via `make lock-order` if intentional")

    # Access-witness verdict: everything the storm's role threads
    # touched must be inside the statically-pinned ownership map, and
    # every map-guarded access must have held the owner's lock. A miss
    # means either a new ownership edge (regenerate via `make
    # thread-roles`, review the diff) or a lock that went missing.
    assert race_witness.observations(), \
        "storm should witness real role-attributed accesses"
    with open(THREAD_ROLES_PINNED) as f:
        roles_pinned = json.load(f)
    assert race_witness.problems(roles_pinned) == []


@pytest.mark.parametrize("n_threads", [8])
def test_event_bus_concurrent_publish(n_threads):
    """The EventBus (reference: RabbitMQ client) under concurrent
    publishers: every message delivered exactly once, no exception."""
    from vodascheduler_tpu.common.events import EventBus, JobEvent
    from vodascheduler_tpu.common.types import EventVerb

    bus = EventBus()
    got = []
    lock = threading.Lock()
    bus.subscribe("stress", lambda ev: (lock.acquire(), got.append(ev),
                                        lock.release()))
    per_thread = 200

    def publish(tid):
        for i in range(per_thread):
            bus.publish("stress", JobEvent(verb=EventVerb.CREATE,
                                           job_name=f"{tid}-{i}"))

    threads = [threading.Thread(target=publish, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
    assert len(got) == n_threads * per_thread
    assert len({ev.job_name for ev in got}) == n_threads * per_thread
