"""vodacheck: the static transition audit — per-rule fixtures, the live
tree, and the re-introduction guarantee (a reverted `job.status =` store
or a blinded booking-release path in scheduler.py must fail the build
again)."""

import io
import json
import os
import textwrap

from vodascheduler_tpu.analysis import vodacheck
from vodascheduler_tpu.common.lifecycle import TRANSITIONS
from vodascheduler_tpu.common.types import JobStatus

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "vodascheduler_tpu")


def findings(src: str, rel: str):
    return vodacheck.check_source(textwrap.dedent(src), rel)


def rules_of(fs):
    return [f.rule for f in fs]


class TestTransitionLiteral:
    def test_valid_call_clean(self):
        assert findings("""
            from vodascheduler_tpu.common import lifecycle
            from vodascheduler_tpu.common.types import JobStatus
            def f(job, tracer):
                lifecycle.transition(job, JobStatus.RUNNING,
                                     reason="scheduled", tracer=tracer)
            """, "scheduler/x.py") == []

    def test_conditional_target_resolved(self):
        # The crash-resume idiom: both literal arms are checked.
        assert findings("""
            from vodascheduler_tpu.common import lifecycle
            from vodascheduler_tpu.common.types import JobStatus
            def f(job, n):
                lifecycle.transition(
                    job,
                    JobStatus.RUNNING if n > 0 else JobStatus.WAITING,
                    reason="resume")
            """, "scheduler/x.py") == []

    def test_unknown_reason_for_target_flagged(self):
        fs = findings("""
            from vodascheduler_tpu.common import lifecycle
            from vodascheduler_tpu.common.types import JobStatus
            def f(job):
                lifecycle.transition(job, JobStatus.RUNNING,
                                     reason="completed")
            """, "scheduler/x.py")
        assert rules_of(fs) == ["transition-literal"]
        assert "completed" in fs[0].message

    def test_target_with_no_inbound_edge_flagged(self):
        # Nothing transitions INTO Submitted — it is the birth state.
        fs = findings("""
            from vodascheduler_tpu.common import lifecycle
            from vodascheduler_tpu.common.types import JobStatus
            def f(job):
                lifecycle.transition(job, JobStatus.SUBMITTED,
                                     reason="resume")
            """, "scheduler/x.py")
        assert rules_of(fs) == ["transition-literal"]
        assert "no declared transition" in fs[0].message

    def test_nonliteral_target_is_itself_a_finding(self):
        fs = findings("""
            from vodascheduler_tpu.common import lifecycle
            def f(job, to):
                lifecycle.transition(job, to, reason="resume")
            """, "scheduler/x.py")
        assert rules_of(fs) == ["transition-literal"]
        assert "not a literal" in fs[0].message

    def test_nonliteral_reason_is_itself_a_finding(self):
        fs = findings("""
            from vodascheduler_tpu.common import lifecycle
            from vodascheduler_tpu.common.types import JobStatus
            def f(job, why):
                lifecycle.transition(job, JobStatus.RUNNING, reason=why)
            """, "scheduler/x.py")
        assert rules_of(fs) == ["transition-literal"]


class TestTransitionCoverage:
    def test_live_table_fully_claimed(self):
        # check_package on the real tree (below) already proves this;
        # here the unit form documents the mechanism.
        claims = set()
        for (frm, to), spec in TRANSITIONS.items():
            for r in spec.reasons:
                claims.add((to, r))
        assert vodacheck._coverage_findings(TRANSITIONS, claims) == []

    def test_unclaimed_edge_flagged(self):
        claims = {(to, r) for (frm, to), spec in TRANSITIONS.items()
                  for r in spec.reasons if to is not JobStatus.CANCELED}
        fs = vodacheck._coverage_findings(TRANSITIONS, claims)
        assert fs and all(f.rule == "transition-unused" for f in fs)
        assert all("Canceled" in f.message for f in fs)

    def test_package_level_coverage_on_fixture_tree(self, tmp_path):
        """End to end: a tree that declares the lifecycle module but
        only ever starts jobs leaves every other edge dead."""
        pkg = tmp_path / "pkg"
        (pkg / "common").mkdir(parents=True)
        (pkg / "common" / "lifecycle.py").write_text("# tables\n")
        (pkg / "scheduler").mkdir()
        (pkg / "scheduler" / "s.py").write_text(textwrap.dedent("""
            from vodascheduler_tpu.common import lifecycle
            from vodascheduler_tpu.common.types import JobStatus
            def f(job):
                lifecycle.transition(job, JobStatus.RUNNING,
                                     reason="scheduled")
            """))
        fs = vodacheck.check_package(str(pkg))
        dead = [f for f in fs if f.rule == "transition-unused"]
        assert dead
        # The claimed edge is covered; unclaimed ones are dead.
        assert not any("'Waiting' -> 'Running'" in f.message
                       for f in dead)
        assert any("'Canceled'" in f.message for f in dead)

    def test_fixture_tree_without_lifecycle_skips_coverage(self, tmp_path):
        pkg = tmp_path / "pkg"
        (pkg / "scheduler").mkdir(parents=True)
        (pkg / "scheduler" / "s.py").write_text("x = 1\n")
        assert vodacheck.check_package(str(pkg)) == []


class TestBookingRelease:
    def test_unprotected_claim_flagged(self):
        fs = findings("""
            class S:
                def go(self, spec, n):
                    self.backend.start_job(spec, n)
            """, "scheduler/x.py")
        assert rules_of(fs) == ["booking-release"]

    def test_locally_protected_claim_clean(self):
        assert findings("""
            class S:
                def go(self, spec, n, name):
                    try:
                        self.backend.scale_job(name, n)
                    except Exception:
                        self.job_num_chips.commit(name, 0)
            """, "scheduler/x.py") == []

    def test_caller_protected_claim_clean(self):
        assert findings("""
            class S:
                def _start(self, spec, n):
                    self.backend.start_job(spec, n)
                def apply(self, spec, n, name):
                    try:
                        self._start(spec, n)
                    except Exception:
                        self._revert(name)
                def _revert(self, name):
                    self.job_num_chips.commit(name, 0)
            """, "scheduler/x.py") == []

    def test_one_unprotected_call_site_flagged(self):
        fs = findings("""
            class S:
                def _start(self, spec, n):
                    self.backend.start_job(spec, n)
                def safe(self, spec, n, name):
                    try:
                        self._start(spec, n)
                    except Exception:
                        self.job_num_chips.release(name)
                def unsafe(self, spec, n):
                    self._start(spec, n)
            """, "scheduler/x.py")
        assert rules_of(fs) == ["booking-release"]
        assert "unsafe" in fs[0].message

    def test_handler_without_ledger_write_flagged(self):
        fs = findings("""
            class S:
                def go(self, spec, n):
                    try:
                        self.backend.start_job(spec, n)
                    except Exception:
                        pass
            """, "scheduler/x.py")
        assert rules_of(fs) == ["booking-release"]

    def test_release_side_stop_exempt(self):
        # stop_job RELEASES chips; a failed stop deliberately keeps the
        # booking for the retry.
        assert findings("""
            class S:
                def go(self, name):
                    self.backend.stop_job(name)
            """, "scheduler/x.py") == []

    def test_rule_scoped_to_scheduler(self):
        assert findings("""
            class B:
                def go(self, spec, n):
                    self.backend.start_job(spec, n)
            """, "cluster/x.py") == []


class TestLiveTree:
    def test_package_checks_clean(self):
        fs = vodacheck.check_package(PKG)
        assert fs == [], "\n".join(
            f"{f.path}:{f.line}: [{f.rule}] {f.message}" for f in fs)

    def test_reintroduced_status_store_fails(self):
        """The acceptance criterion: revert one of the eight removed
        `job.status =` sites (in memory) — vodacheck must fail."""
        with open(os.path.join(PKG, "scheduler", "scheduler.py")) as f:
            src = f.read()
        broken = src + (
            "\n\ndef _backslide(job):\n"
            "    job.status = JobStatus.WAITING\n")
        fs = vodacheck.check_source(broken, "scheduler/scheduler.py")
        assert any(f.rule == "status-store" for f in fs)

    def test_undeclared_transition_reason_fails(self):
        with open(os.path.join(PKG, "scheduler", "scheduler.py")) as f:
            src = f.read()
        broken = src.replace('reason="scheduled"', 'reason="because"')
        assert broken != src
        fs = vodacheck.check_source(broken, "scheduler/scheduler.py")
        assert any(f.rule == "transition-literal"
                   and "because" in f.message for f in fs)

    def test_blinding_a_booking_release_fails(self):
        """Append a claim path with no dominating release to the REAL
        Scheduler class — the exception-edge contract must fail."""
        with open(os.path.join(PKG, "scheduler", "scheduler.py")) as f:
            src = f.read()
        # scheduler.py ends inside `class Scheduler`; this continues it.
        broken = src + (
            "\n    def _unreleased_claim(self, spec, n):\n"
            "        self.backend.start_job(spec, n)\n")
        fs = vodacheck.check_source(broken, "scheduler/scheduler.py")
        assert any(f.rule == "booking-release"
                   and "_unreleased_claim" in f.message for f in fs)

    def test_cli_jsonl_output(self, tmp_path):
        bad = tmp_path / "pkg" / "scheduler"
        bad.mkdir(parents=True)
        (bad / "x.py").write_text(
            "class S:\n    def go(self, spec, n):\n"
            "        self.backend.start_job(spec, n)\n")
        out = io.StringIO()
        rc = vodacheck.run([str(tmp_path / "pkg")], fmt="jsonl",
                           stream=out)
        assert rc == 1
        recs = [json.loads(line) for line in
                out.getvalue().strip().splitlines()]
        assert recs and recs[0]["rule"] == "booking-release"
