"""The concurrent actuation plane (decide/actuate lock split).

Pins the three headline properties of the engine:

1. **Critical path, not sum** — a pass touching K jobs costs the slowest
   wave member. On the fake backend with 6 jobs resizing at a modeled
   0.2 s actuation latency each, one pass completes in ≈ max (< 2× a
   single actuation), not the 1.2 s serial sum, and
   `voda_scheduler_resched_latency_seconds` reflects it.
2. **Liveness** — `status_table()` (and the REST route over it) returns
   while an actuation is in flight, because the scheduler lock is
   released during backend calls; job events racing the pass are
   deferred to the commit point, never lost, and never leave
   double-booked chips.
3. **Real-clock re-trigger** — a trigger arriving while the rate-limit
   window is closed (or mid-pass) re-arms on the REAL clock too; the
   pass runs without anyone calling pump() (the old gap silently waited
   for the next daemon poll tick).
"""

import threading
import time
import urllib.request

from vodascheduler_tpu.allocator import ResourceAllocator
from vodascheduler_tpu.cluster.backend import ClusterEvent, ClusterEventKind
from vodascheduler_tpu.cluster.fake import FakeClusterBackend, WorkloadProfile
from vodascheduler_tpu.common.clock import Clock, VirtualClock
from vodascheduler_tpu.common.events import EventBus
from vodascheduler_tpu.common.job import JobConfig, JobSpec
from vodascheduler_tpu.common.store import JobStore
from vodascheduler_tpu.placement import PlacementManager
from vodascheduler_tpu.scheduler import Scheduler
from vodascheduler_tpu.service import AdmissionService

NUM_JOBS = 6
ACTUATION_LATENCY = 0.2


def _spec(name, max_chips=4, epochs=1000):
    return JobSpec(name=name, pool="pool",
                   config=JobConfig(min_num_chips=1, max_num_chips=max_chips,
                                    epochs=epochs))


def _world(num_hosts=NUM_JOBS, chips_per_host=2, rate_limit=30.0,
           clock=None, parallel=True, latency=0.0):
    clock = clock or VirtualClock(start=1753760000.0)
    store = JobStore()
    bus = EventBus()
    backend = FakeClusterBackend(clock, restart_overhead_seconds=5.0,
                                 inplace_overhead_seconds=0.5,
                                 actuation_latency_seconds=latency)
    for i in range(num_hosts):
        backend.add_host(f"host-{i}", chips_per_host, announce=False)
    sched = Scheduler("pool", backend, store, ResourceAllocator(store),
                      clock, bus=bus,
                      placement_manager=PlacementManager("pool"),
                      algorithm="ElasticFIFO", rate_limit_seconds=rate_limit,
                      actuation_parallel=parallel)
    admission = AdmissionService(store, bus, clock)
    return clock, store, bus, backend, sched, admission


class TestCriticalPathLatency:
    def test_six_resizes_cost_max_not_sum(self):
        """6 same-host grows at a modeled 0.2 s backend call each: the
        claim wave overlaps them, so the pass's wall time sits near one
        actuation, nowhere near the 1.2 s serial sum."""
        clock, store, bus, backend, sched, admission = _world()
        for i in range(NUM_JOBS):
            backend.register_profile(
                f"j{i}", WorkloadProfile(epoch_seconds_at_1=600.0))
            admission.create_training_job(_spec(f"j{i}"))
        # Drain the submission passes (cheap: latency knob still 0).
        for _ in range(4):
            clock.advance(31.0)
        assert all(sched.job_num_chips[j] == 2
                   for j in sched.job_num_chips), sched.job_num_chips
        assert len(sched.job_num_chips) == NUM_JOBS

        # Re-announce every host at double capacity while the rate
        # window is closed: all six HOST_ADDED triggers coalesce into
        # ONE pass, in which every job grows 2 -> 4 on its own host.
        sched.trigger_resched("manual")
        clock.advance(0.0)
        for i in range(NUM_JOBS):
            backend.add_host(f"host-{i}", 4)
        backend.actuation_latency_seconds = ACTUATION_LATENCY
        before_total = sched.m_resched_total.value()
        before_b = sched.h_resched_latency.bucket_counts(phase="actuate")

        t0 = time.monotonic()
        clock.advance(31.0)  # fires exactly the coalesced grow pass
        wall = time.monotonic() - t0

        assert sched.m_resched_total.value() == before_total + 1
        assert all(sched.job_num_chips[j] == 4
                   for j in sched.job_num_chips), sched.job_num_chips
        # Critical path: well under the 1.2 s sum; < 2x one actuation.
        assert wall < 2 * ACTUATION_LATENCY, (
            f"pass took {wall:.3f}s — actuation did not overlap "
            f"(serial sum would be {NUM_JOBS * ACTUATION_LATENCY:.1f}s)")
        # The latency histogram saw the same story: the actuate-half
        # observation (the waves are the whole cost here) landed at or
        # below the 0.5 s bound.
        after_b = sched.h_resched_latency.bucket_counts(phase="actuate")
        assert after_b[0.5] == before_b[0.5] + 1

        # The audit record carries the wave evidence: one parallel claim
        # wave of 6, priced at max (one in-place resize) not sum.
        rec = sched.audit_records(1)[0]
        act = rec["actuation"]
        waves = {w["wave"]: w for w in act["waves"]}
        assert waves["claim"]["jobs"] == NUM_JOBS
        assert waves["claim"]["parallel"] is True
        assert waves["claim"]["critical_path_s"] < \
            waves["claim"]["serial_sum_s"]
        # Modeled price: inplace overhead (0.5) + call latency (0.2) per
        # job; the wave prices at one member, the serial sum at six.
        assert abs(waves["claim"]["critical_path_s"] - 0.7) < 1e-6
        assert abs(waves["claim"]["serial_sum_s"] - 0.7 * NUM_JOBS) < 1e-6
        assert sched.actuation_serial_sum_seconds_total > \
            sched.actuation_critical_path_seconds_total > 0


class TestDecideActuateLiveness:
    def test_status_and_rest_read_during_inflight_actuation(self):
        """While a slow actuation pass is in flight: status_table() and
        the REST route return without waiting; a JOB_COMPLETED racing
        the pass is deferred, not lost; after commit nothing is
        double-booked and the lock is free."""
        from vodascheduler_tpu.common.metrics import Registry
        from vodascheduler_tpu.service.rest import make_scheduler_server

        clock, store, bus, backend, sched, admission = _world(
            latency=0.0)
        for i in range(NUM_JOBS):
            backend.register_profile(
                f"j{i}", WorkloadProfile(epoch_seconds_at_1=600.0))
            admission.create_training_job(_spec(f"j{i}"))
        for _ in range(4):
            clock.advance(31.0)
        assert len(sched.job_num_chips) == NUM_JOBS

        server = make_scheduler_server(sched, Registry(), host="127.0.0.1",
                                       port=0)
        server.start()
        try:
            # Arm a slow coalesced pass (same grow shape as above).
            sched.trigger_resched("manual")
            clock.advance(0.0)
            for i in range(NUM_JOBS):
                backend.add_host(f"host-{i}", 4)
            backend.actuation_latency_seconds = 0.5

            pass_done = threading.Event()

            def run_pass():
                clock.advance(31.0)
                pass_done.set()

            runner = threading.Thread(target=run_pass, daemon=True)
            runner.start()
            # Wait until the pass is actually in flight.
            deadline = time.monotonic() + 5.0
            while not sched._in_resched and time.monotonic() < deadline:
                time.sleep(0.005)
            assert sched._in_resched, "pass never started"

            # 1) Direct read: returns in milliseconds, not after the
            #    ~0.5 s wave.
            t0 = time.monotonic()
            table = sched.status_table()
            read_wall = time.monotonic() - t0
            assert len(table) == NUM_JOBS
            assert read_wall < 0.2, (
                f"status_table blocked {read_wall:.3f}s on actuation")

            # 2) REST read over the same state.
            t0 = time.monotonic()
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/training",
                    timeout=5.0) as resp:
                assert resp.status == 200
            assert time.monotonic() - t0 < 0.4

            # 3) A completion racing the in-flight pass: deferred to the
            #    commit point, then applied — never interleaved, never
            #    lost.
            victim = sorted(sched.job_num_chips)[0]
            backend.emit(ClusterEvent(ClusterEventKind.JOB_COMPLETED,
                                      victim, timestamp=clock.now()))
            assert victim not in sched.done_jobs  # still deferred

            assert pass_done.wait(timeout=10.0), "actuation pass hung"
            assert victim in sched.done_jobs
            assert victim not in sched.job_num_chips

            # Post-commit coherence: within capacity, books match the
            # backend's live view (modulo the completed job), lock free.
            live = backend.running_jobs()
            total = sum(backend.list_hosts().values())
            assert sum(sched.job_num_chips.values()) <= total
            for name, chips in sched.job_num_chips.items():
                if chips > 0 and name in live:
                    assert live[name].num_workers == chips
            assert sched._lock.acquire(timeout=5.0), "scheduler lock leaked"
            sched._lock.release()
        finally:
            backend.actuation_latency_seconds = 0.0
            server.stop()


class TestRealClockRetrigger:
    def test_blocked_trigger_fires_without_pump(self):
        """Real clock, no daemon: a trigger landing inside the closed
        rate-limit window must still run once the window opens — via the
        real-clock timer the commit/trigger paths now arm (the old code
        only re-armed under a VirtualClock and silently waited for the
        next pump)."""
        clock, store, bus, backend, sched, admission = _world(
            clock=Clock(), rate_limit=0.3)
        backend.register_profile("a", WorkloadProfile(
            epoch_seconds_at_1=3600.0))
        admission.create_training_job(_spec("a"))  # pass 1, inline
        assert sched.m_resched_total.value() == 1
        # Inside the window: goes pending, arms a wall-clock timer.
        sched.trigger_resched("manual")
        assert sched.resched_pending
        assert sched.m_resched_total.value() == 1
        deadline = time.monotonic() + 5.0
        while sched.m_resched_total.value() < 2 and \
                time.monotonic() < deadline:
            time.sleep(0.02)
        assert sched.m_resched_total.value() >= 2, (
            "blocked trigger never ran without pump()")

    def test_midpass_retrigger_fires_without_pump(self):
        """A re-trigger arriving DURING a pass (the exact
        scheduler.py:449 gap): the commit point must arm a real-clock
        timer for it."""
        clock, store, bus, backend, sched, admission = _world(
            clock=Clock(), rate_limit=0.3)
        backend.register_profile("a", WorkloadProfile(
            epoch_seconds_at_1=3600.0))

        fired = {"done": False}
        orig_start = backend.start_job

        def retrigger_start(spec, n, placements=None):
            orig_start(spec, n, placements)
            if not fired["done"]:
                fired["done"] = True
                # Mid-pass: _in_resched is True, so this only goes
                # pending; the commit point must re-arm it.
                sched.trigger_resched("manual")

        backend.start_job = retrigger_start
        admission.create_training_job(_spec("a"))
        assert fired["done"]
        assert sched.m_resched_total.value() == 1
        deadline = time.monotonic() + 5.0
        while sched.m_resched_total.value() < 2 and \
                time.monotonic() < deadline:
            time.sleep(0.02)
        assert sched.m_resched_total.value() >= 2, (
            "mid-pass re-trigger never ran without pump()")
