"""Multi-pool composition + topology threading (VERDICT r2 items 5 & 8).

The reference deploys one scheduler per GPU type (helm/voda-scheduler/,
scheduler.go:189-190); here `VodaApp(pools=...)` composes N schedulers
over the shared store/bus, and each backend hands its pool topology to
supervisors via VODA_TOPOLOGY so mesh planning respects the pool's real
host block.
"""

import json
import time
import urllib.request

import pytest

from tests import helpers
from vodascheduler_tpu.placement.topology import PoolTopology
from vodascheduler_tpu.service.app import PoolSpec, VodaApp, parse_pools


class TestParsePools:
    def test_topology_and_count_entries(self):
        pools = parse_pools("v5p=4x4x4/2x2x1,v5e=16", "ElasticTiresias")
        assert [p.name for p in pools] == ["v5p", "v5e"]
        assert pools[0].topology.torus_dims == (4, 4, 4)
        assert pools[0].topology.chips_per_host == 4
        assert pools[0].algorithm == "ElasticTiresias"
        assert pools[1].topology is None and pools[1].chips == 16

    def test_per_pool_algorithm_suffix(self):
        pools = parse_pools("a=8:ElasticFIFO,b=4", "ElasticTiresias")
        assert pools[0].algorithm == "ElasticFIFO"
        assert pools[1].algorithm == "ElasticTiresias"

    def test_empty_rejected(self):
        with pytest.raises(ValueError):
            parse_pools(" , ", "FIFO")


class TestTopologyRoundTrip:
    def test_str_parse(self):
        topo = PoolTopology(torus_dims=(4, 4, 4), host_block=(2, 2, 1))
        assert str(topo) == "4x4x4/2x2x1"
        back = PoolTopology.parse(str(topo))
        assert back.torus_dims == topo.torus_dims
        assert back.host_block == topo.host_block


class TestTopologyReachesMeshPlanning:
    """SURVEY §2.3 / §7: tp must stay inside a host's chips whatever the
    pool's host block is — a v5e-style 1-chip-per-host pool must plan
    tp=1 even for a model big enough to want tp."""

    def test_plan_mesh_respects_host_block(self):
        from vodascheduler_tpu.parallel.mesh import plan_mesh
        v5e_1chip = PoolTopology(torus_dims=(8,), host_block=(1,))
        plan = plan_mesh(8, model_params_b=8.0, topology=v5e_1chip)
        assert plan.tp == 1          # tp may not cross hosts
        assert plan.fsdp == 8
        v5p = PoolTopology(torus_dims=(4, 4, 4), host_block=(2, 2, 1))
        plan = plan_mesh(8, model_params_b=8.0, topology=v5p)
        assert plan.tp == 4          # full host block available

    def test_slice_shape_pins_chip_count(self):
        from vodascheduler_tpu.parallel.mesh import plan_mesh
        topo = PoolTopology(torus_dims=(4, 4, 4), host_block=(2, 2, 1))
        plan = plan_mesh(999, model_params_b=0.0, topology=topo,
                         slice_shape=topo.slice_for(8))
        assert plan.num_chips == 8

    @pytest.mark.skipif(not helpers.JAX_HAS_ABSTRACT_MESH,
                        reason=helpers.NEEDS_ABSTRACT_MESH)
    def test_train_setup_uses_topology(self):
        # params_b >= 1 wants tp; a 1-chip-per-host pool forbids it.
        from vodascheduler_tpu.models import get_model
        from vodascheduler_tpu.runtime.train import make_train_setup
        bundle = get_model("llama_tiny")
        bundle.params_b = 2.0  # plan-time scale only; module stays tiny
        topo = PoolTopology(torus_dims=(4,), host_block=(1,))
        setup = make_train_setup(bundle, 4, topology=topo)
        assert setup.plan.tp == 1
        assert setup.plan.fsdp == 4

    def test_backend_exports_topology_env(self, tmp_path, monkeypatch):
        """LocalBackend hands VODA_TOPOLOGY to every supervisor spawn."""
        import vodascheduler_tpu.cluster.local as local_mod
        captured = {}

        class FakePopen:
            def __init__(self, cmd, env=None, **kw):
                captured["env"] = env
            def poll(self):
                return 0
            def send_signal(self, sig):
                pass
            def wait(self, timeout=None):
                return 0
            def kill(self):
                pass

        monkeypatch.setattr(local_mod.subprocess, "Popen", FakePopen)
        topo = PoolTopology(torus_dims=(4, 4, 4), host_block=(2, 2, 1))
        be = local_mod.LocalBackend(str(tmp_path), chips=4,
                                    hermetic_devices=2, topology=topo)
        from vodascheduler_tpu.common.job import JobSpec
        be.start_job(JobSpec(name="j", model="mnist_mlp"), 2)
        assert captured["env"]["VODA_TOPOLOGY"] == "4x4x4/2x2x1"
        be.close()


@pytest.fixture()
def two_pool_app(tmp_path):
    app = VodaApp(workdir=str(tmp_path), hermetic_devices=2,
                  pools=[PoolSpec(name="v5p", chips=4,
                                  algorithm="ElasticFIFO"),
                         PoolSpec(name="v5e", chips=2,
                                  algorithm="ElasticFIFO")],
                  service_port=0, scheduler_port=0, allocator_port=0,
                  rate_limit_seconds=0.2, collector_interval_seconds=3600.0)
    app.start()
    yield app
    app.stop()


def _req(url, method="GET", body=None):
    data = body.encode() if body else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10.0) as r:
        return json.loads(r.read().decode())


class TestTwoPoolApp:
    def test_jobs_route_by_pool_and_complete(self, two_pool_app):
        app = two_pool_app
        base = f"http://127.0.0.1:{app.service_server.port}"
        for pool in ("v5p", "v5e"):
            _req(f"{base}/training", "POST", json.dumps({
                "name": f"job-{pool}", "pool": pool, "model": "mnist_mlp",
                "config": {"min_num_chips": 1, "max_num_chips": 2,
                           "epochs": 1},
                "steps_per_epoch": 1, "global_batch_size": 4,
            }))
        deadline = time.time() + 120
        while time.time() < deadline:
            jobs = _req(f"{base}/training")
            if jobs and all(j["status"] == "Completed" for j in jobs):
                break
            time.sleep(1.0)
        states = {j["pool"]: j["status"] for j in _req(f"{base}/training")}
        assert states == {"v5p": "Completed", "v5e": "Completed"}
        # Each pool's scheduler saw only its own job.
        sched_base = f"http://127.0.0.1:{app.scheduler_server.port}"
        for pool in ("v5p", "v5e"):
            table = _req(f"{sched_base}/training?pool={pool}")
            assert len(table) == 1
            assert pool in table[0]["name"]

    def test_unknown_pool_rejected_at_admission(self, two_pool_app):
        # The bus queues events for unsubscribed topics silently, so an
        # unvalidated typo'd pool would be accepted and stuck forever.
        app = two_pool_app
        base = f"http://127.0.0.1:{app.service_server.port}"
        try:
            _req(f"{base}/training", "POST", json.dumps({
                "name": "ghost", "pool": "nope", "model": "mnist_mlp"}))
            code = 200
        except urllib.error.HTTPError as e:
            code = e.code
        assert code == 400
        assert all("ghost" not in j["name"]
                   for j in _req(f"{base}/training"))

    def test_scheduler_routes_and_pools_endpoint(self, two_pool_app):
        app = two_pool_app
        base = f"http://127.0.0.1:{app.scheduler_server.port}"
        pools = _req(f"{base}/pools")
        assert set(pools) == {"v5p", "v5e"}
        assert pools["v5p"]["total_chips"] == 4
        assert pools["v5e"]["total_chips"] == 2
        # Ambiguous request without ?pool= is a 400.
        try:
            _req(f"{base}/training")
            raised = False
        except urllib.error.HTTPError as e:
            raised = e.code == 400
        assert raised
        # Per-pool algorithm PUT only touches that pool.
        _req(f"{base}/algorithm?pool=v5e", "PUT",
             json.dumps({"algorithm": "ElasticTiresias"}))
        assert app.schedulers["v5e"].algorithm == "ElasticTiresias"
        assert app.schedulers["v5p"].algorithm == "ElasticFIFO"
