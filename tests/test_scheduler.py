"""End-to-end scheduler tests on the fake cluster under simulated time.

These are the hermetic elasticity/migration/churn scenarios the reference
could only exercise against a live Kubernetes cluster (SURVEY.md §4).
"""

import pytest

from vodascheduler_tpu.allocator import ResourceAllocator
from vodascheduler_tpu.cluster.fake import FakeClusterBackend, WorkloadProfile
from vodascheduler_tpu.common.clock import VirtualClock
from vodascheduler_tpu.common.events import EventBus
from vodascheduler_tpu.common.job import JobConfig, JobSpec
from vodascheduler_tpu.common.store import JobStore
from vodascheduler_tpu.common.types import JobStatus
from vodascheduler_tpu.placement import PlacementManager
from vodascheduler_tpu.scheduler import Scheduler
from vodascheduler_tpu.service import AdmissionService


def build_world(num_hosts=2, chips_per_host=4, algorithm="ElasticFIFO",
                rate_limit=1.0, restart_overhead=5.0, placement=True,
                store=None, resume=False, backend=None, clock=None):
    clock = clock or VirtualClock(start=1753760000.0)
    store = store if store is not None else JobStore()
    bus = EventBus()
    if backend is None:
        backend = FakeClusterBackend(clock, restart_overhead_seconds=restart_overhead)
        for i in range(num_hosts):
            backend.add_host(f"host-{i}", chips_per_host, announce=False)
    pm = PlacementManager("pool") if placement else None
    allocator = ResourceAllocator(store)
    sched = Scheduler("pool", backend, store, allocator, clock, bus=bus,
                      placement_manager=pm, algorithm=algorithm,
                      rate_limit_seconds=rate_limit, resume=resume)
    admission = AdmissionService(store, bus, clock)
    return clock, store, bus, backend, sched, admission


def spec(name, min_chips=1, max_chips=4, epochs=5, pool="pool", priority=0):
    return JobSpec(name=name, pool=pool, priority=priority,
                   config=JobConfig(min_num_chips=min_chips,
                                    max_num_chips=max_chips, epochs=epochs))


class TestEndToEnd:
    def test_single_job_runs_to_completion(self):
        clock, store, bus, backend, sched, admission = build_world()
        backend.register_profile("j", WorkloadProfile(epoch_seconds_at_1=60.0))
        name = admission.create_training_job(spec("j", max_chips=8, epochs=3))

        job = store.get_job(name)
        assert job.status == JobStatus.RUNNING
        assert sched.job_num_chips[name] == 8  # elastic: all chips

        # 3 epochs * 60s serial at speedup(8)=8^0.9≈6.5 → ~28s + overhead
        clock.advance(3600.0)
        assert name in backend.completed
        job = store.get_job(name)
        assert job.status == JobStatus.COMPLETED
        assert name in sched.done_jobs
        assert sched.job_num_chips == {}

    def test_two_jobs_share_elastically_then_first_finishes(self):
        clock, store, bus, backend, sched, admission = build_world(
            num_hosts=2, chips_per_host=4)
        backend.register_profile("short", WorkloadProfile(epoch_seconds_at_1=10.0))
        backend.register_profile("long", WorkloadProfile(epoch_seconds_at_1=600.0))
        a = admission.create_training_job(spec("short", max_chips=8, epochs=2))
        clock.advance(2.0)
        b = admission.create_training_job(spec("long", max_chips=8, epochs=200))
        clock.advance(2.0)  # let the rate-limited resched fire

        # both running, sharing 8 chips
        assert sched.job_num_chips[a] > 0
        assert sched.job_num_chips[b] > 0
        assert sum(sched.job_num_chips.values()) == 8

        clock.advance(7200.0)
        assert a in backend.completed
        # after a finishes, b expands to all 8
        assert sched.job_num_chips[b] == 8
        clock.advance(100000.0)
        assert b in backend.completed

    def test_fifo_queues_when_full(self):
        clock, store, bus, backend, sched, admission = build_world(
            num_hosts=1, chips_per_host=4, algorithm="FIFO")
        a = admission.create_training_job(spec("a", min_chips=4, epochs=2))
        clock.advance(2.0)
        b = admission.create_training_job(spec("b", min_chips=4, epochs=2))
        clock.advance(2.0)
        assert sched.job_num_chips[a] == 4
        assert sched.job_num_chips[b] == 0
        assert store.get_job(b).status == JobStatus.WAITING
        clock.advance(3600.0)
        assert a in backend.completed
        assert b in backend.completed  # b started after a finished

    def test_delete_running_job(self):
        clock, store, bus, backend, sched, admission = build_world()
        name = admission.create_training_job(spec("doomed", epochs=100))
        clock.advance(5.0)
        assert sched.job_num_chips[name] > 0
        admission.delete_training_job(name)
        clock.advance(5.0)
        job = store.get_job(name)
        assert job.status == JobStatus.CANCELED
        assert name not in backend.running_jobs()

    def test_job_failure_is_terminal(self):
        clock, store, bus, backend, sched, admission = build_world()
        backend.register_profile(
            "crashy", WorkloadProfile(epoch_seconds_at_1=10.0, fail_at_epoch=2))
        name = admission.create_training_job(spec("crashy", epochs=10))
        clock.advance(3600.0)
        assert name in backend.failed
        assert store.get_job(name).status == JobStatus.FAILED
        assert name in sched.done_jobs

    def test_rate_limit_coalesces(self):
        clock, store, bus, backend, sched, admission = build_world(rate_limit=30.0)
        a = admission.create_training_job(spec("a", epochs=50))
        before = sched.m_resched_total.value()
        # 3 more submissions inside the rate window → exactly 1 more resched
        for n in ("b", "c", "d"):
            admission.create_training_job(spec(n, epochs=50))
        clock.advance(31.0)
        after = sched.m_resched_total.value()
        assert after == before + 1


class TestElasticity:
    def test_scale_down_on_contention_and_restart_overhead(self):
        clock, store, bus, backend, sched, admission = build_world(
            num_hosts=2, chips_per_host=4, restart_overhead=5.0)
        a = admission.create_training_job(spec("a", max_chips=8, epochs=100))
        clock.advance(2.0)
        assert sched.job_num_chips[a] == 8
        restarts_before = backend.jobs[a].restarts
        b = admission.create_training_job(spec("b", max_chips=8, epochs=100))
        clock.advance(2.0)
        # a shrank (checkpoint-restart), b started
        assert sched.job_num_chips[a] == 4
        assert sched.job_num_chips[b] == 4
        assert backend.jobs[a].restarts == restarts_before + 1

    def test_chips_returned_on_completion_go_to_survivor(self):
        clock, store, bus, backend, sched, admission = build_world(
            num_hosts=2, chips_per_host=4)
        backend.register_profile("quick", WorkloadProfile(epoch_seconds_at_1=5.0))
        survivor = admission.create_training_job(spec("steady", max_chips=8, epochs=1000))
        clock.advance(2.0)
        quick = admission.create_training_job(spec("quick", max_chips=4, epochs=2))
        clock.advance(3600.0)
        assert quick in backend.completed
        assert sched.job_num_chips[survivor] == 8


class TestHostChurn:
    def test_host_removed_shrinks_capacity(self):
        clock, store, bus, backend, sched, admission = build_world(
            num_hosts=2, chips_per_host=4)
        a = admission.create_training_job(spec("a", max_chips=8, epochs=1000))
        clock.advance(2.0)
        assert sched.job_num_chips[a] == 8
        backend.remove_host("host-1")
        clock.advance(5.0)
        assert sched.total_chips == 4
        assert sched.job_num_chips[a] == 4

    def test_host_added_grows_capacity(self):
        clock, store, bus, backend, sched, admission = build_world(
            num_hosts=1, chips_per_host=4)
        a = admission.create_training_job(spec("a", max_chips=8, epochs=1000))
        clock.advance(2.0)
        assert sched.job_num_chips[a] == 4
        backend.add_host("host-new", 4)
        clock.advance(5.0)
        assert sched.total_chips == 8
        assert sched.job_num_chips[a] == 8


class TestTiresias:
    def test_long_running_job_demoted(self):
        clock, store, bus, backend, sched, admission = build_world(
            num_hosts=1, chips_per_host=4, algorithm="Tiresias")
        name = admission.create_training_job(spec("hog", min_chips=4, epochs=10000))
        clock.advance(2.0)
        job = store.get_job(name)
        assert job.priority == 0
        # chip time = 4 chips * t; threshold 3600 chip-seconds → ~900s
        clock.advance(1200.0)
        assert sched.ready_jobs[name].priority == 1

    def test_starved_job_promoted(self):
        clock, store, bus, backend, sched, admission = build_world(
            num_hosts=1, chips_per_host=4, algorithm="Tiresias")
        # Force a demoted waiting job: submit with priority 1 directly.
        name = admission.create_training_job(
            spec("starved", min_chips=4, epochs=10000, priority=1))
        hog = admission.create_training_job(spec("hog", min_chips=4, epochs=10000))
        clock.advance(2.0)
        # hog (priority 0, earlier start... both at queue) — whichever runs,
        # the waiting one starves and must be promoted to priority 0.
        waiting = name if sched.job_num_chips.get(name, 0) == 0 else hog
        clock.advance(600.0)
        assert sched.ready_jobs[waiting].priority == 0


class TestResume:
    def test_scheduler_restart_reconstructs_state(self):
        clock, store, bus, backend, sched, admission = build_world()
        a = admission.create_training_job(spec("a", max_chips=8, epochs=1000))
        clock.advance(10.0)
        assert sched.job_num_chips[a] == 8
        sched.stop()

        # New scheduler process, same store + live backend (resume path).
        clock2 = clock  # same world clock
        allocator = ResourceAllocator(store)
        pm = PlacementManager("pool")
        for h, c in backend.list_hosts().items():
            pm.add_host(h, c)
        sched2 = Scheduler("pool", backend, store, allocator, clock2,
                           placement_manager=pm, algorithm="ElasticFIFO",
                           rate_limit_seconds=1.0, resume=True)
        assert a in sched2.ready_jobs
        assert sched2.job_num_chips[a] == 8
        assert sched2.ready_jobs[a].status == JobStatus.RUNNING
        # it keeps running to completion under the new scheduler
        clock.advance(10.0)
        assert a in backend.running_jobs()


class SimulatedCrash(BaseException):
    """kill -9 stand-in: a BaseException sails past every `except
    Exception` isolation layer in the scheduler, so the process dies with
    whatever the backend and store had durably absorbed — exactly the
    state a real SIGKILL leaves behind."""


def _assert_no_double_booking(backend, sched):
    """Backend truth: per-host booked chips never exceed capacity, and
    the scheduler's books match the backend's live view."""
    hosts = backend.list_hosts()
    booked = {h: 0 for h in hosts}
    live = backend.running_jobs()
    for handle in live.values():
        for host, workers in handle.placements:
            if host in booked:
                booked[host] += workers
    for host, used in booked.items():
        assert used <= hosts[host], (
            f"host {host} double-booked: {used}/{hosts[host]}")
    total = sum(hosts.values())
    assert sum(sched.job_num_chips.values()) <= total
    for name, handle in live.items():
        if name in sched.job_num_chips:
            assert sched.job_num_chips[name] == handle.num_workers, (
                f"{name}: booked {sched.job_num_chips[name]} vs live "
                f"{handle.num_workers}")


@pytest.mark.slow
class TestCrashConsistency:
    def test_kill_mid_resched_under_event_storm_then_resume(self, tmp_path):
        """Crash-consistency proof for the single-replica control plane
        (reference: constructStatusOnRestart, scheduler.go:1009-1072 +
        helm resumeEnabled): the scheduler is killed MID-RESCHED — after
        the backend realized some of the pass's starts but before the
        rest — under an event storm (job churn + host churn). A fresh
        scheduler resuming from the durable store and the backend's live
        view must come back with no double-booked chips and no stranded
        jobs: every job still runs to completion."""
        from vodascheduler_tpu.common.store import FileJobStore

        clock = VirtualClock(start=1753760000.0)
        store_path = str(tmp_path / "jobs.json")
        store = FileJobStore(store_path)  # autoflush: durable per update
        backend = FakeClusterBackend(clock, restart_overhead_seconds=2.0)
        for i in range(4):
            backend.add_host(f"host-{i}", 4, announce=False)
        backend.register_profile("j", WorkloadProfile(epoch_seconds_at_1=50.0))
        clock2, store2, bus, backend2, sched, admission = build_world(
            store=store, backend=backend, clock=clock, rate_limit=5.0)
        assert backend2 is backend and clock2 is clock

        # Arm the crash: the 12th start/scale the backend REALIZES kills
        # the control plane right after the pods exist — the classic
        # torn-apply window (bookkeeping for later starts never happens).
        # By call 12 the storm has seen arrivals, elastic resizes AND the
        # host-churn events below.
        calls = {"n": 0}
        real_start, real_scale = backend.start_job, backend.scale_job

        def crashing_start(spec, n, placements=None):
            real_start(spec, n, placements)
            calls["n"] += 1
            if calls["n"] == 12:
                raise SimulatedCrash()

        def crashing_scale(name, n, placements=None):
            real_scale(name, n, placements)
            calls["n"] += 1
            if calls["n"] == 12:
                raise SimulatedCrash()

        backend.start_job = crashing_start
        backend.scale_job = crashing_scale

        # The event storm: a dozen jobs arriving in waves while a host
        # dies and returns — every wave triggers rescheds.
        crashed = False
        created = []
        try:
            for i in range(12):
                created.append(admission.create_training_job(spec(
                    f"j-{i:02d}", min_chips=1, max_chips=4, epochs=3)))
                clock.advance(3.0)
                if i == 2:
                    backend.remove_host("host-3")
                if i == 4:
                    backend.add_host("host-3", 4)
        except SimulatedCrash:
            crashed = True
        assert crashed, "the storm never reached the crash point"
        sched.stop()  # the dead process runs no more timers
        backend.start_job, backend.scale_job = real_start, real_scale

        # Workers keep training while the control plane is down (pods
        # don't die with the scheduler); time passes before the restart.
        clock.advance(30.0)

        # Resume: fresh store loaded from disk, fresh placement manager
        # rebuilt from the backend's live placements, same cluster.
        store_resumed = FileJobStore(store_path)
        pm = PlacementManager("pool")
        for h, c in backend.list_hosts().items():
            pm.add_host(h, c)
        sched2 = Scheduler("pool", backend, store_resumed,
                           ResourceAllocator(store_resumed), clock,
                           placement_manager=pm, algorithm="ElasticFIFO",
                           rate_limit_seconds=5.0, resume=True)

        # Every job admitted before the crash is durably known (the jobs
        # after the crash point were never submitted — the client died
        # with the process) and accounted for — ready or done, never
        # lost.
        known = {j.name for j in store_resumed.list_jobs(pool="pool")}
        assert known == set(created)
        tracked = set(sched2.ready_jobs) | set(sched2.done_jobs)
        assert known == tracked
        _assert_no_double_booking(backend, sched2)

        # No stranded jobs: everything runs to completion under the
        # resumed scheduler, with the booking invariant held throughout.
        for _ in range(80):
            clock.advance(50.0)
            _assert_no_double_booking(backend, sched2)
            jobs = store_resumed.list_jobs(pool="pool")
            if all(j.status == JobStatus.COMPLETED for j in jobs):
                break
        jobs = store_resumed.list_jobs(pool="pool")
        incomplete = [j.name for j in jobs if j.status != JobStatus.COMPLETED]
        assert not incomplete, f"stranded jobs after resume: {incomplete}"
        assert len(jobs) == len(created) >= 5


class TestMetricsAccounting:
    def test_waiting_and_running_seconds_accrue(self):
        clock, store, bus, backend, sched, admission = build_world(
            num_hosts=1, chips_per_host=4, algorithm="FIFO")
        a = admission.create_training_job(spec("a", min_chips=4, epochs=1000))
        clock.advance(2.0)
        b = admission.create_training_job(spec("b", min_chips=4, epochs=1000))
        clock.advance(100.0)
        ja, jb = sched.ready_jobs[a], sched.ready_jobs[b]
        assert ja.metrics.running_seconds > 90
        assert ja.metrics.chip_seconds > 4 * 90
        assert jb.metrics.waiting_seconds > 90
        assert jb.metrics.running_seconds == 0


class TestMultiPool:
    def test_two_pools_route_and_run_independently(self):
        """Reference layout: one scheduler instance per GPU type, sharing
        the store and the event bus, with admission routing each job to
        its pool's queue (SURVEY.md §1 layer map; rabbitmq.go per-type
        queues). Here: two pools, one control plane."""
        clock = VirtualClock(start=1753760000.0)
        store = JobStore()
        bus = EventBus()

        backends = {}
        scheds = {}
        for pool, chips in (("v5p-pool", 8), ("v5e-pool", 4)):
            be = FakeClusterBackend(clock, restart_overhead_seconds=5.0)
            be.add_host(f"{pool}-host-0", chips, announce=False)
            backends[pool] = be
            scheds[pool] = Scheduler(pool, be, store,
                                     ResourceAllocator(store), clock,
                                     bus=bus, algorithm="ElasticFIFO",
                                     rate_limit_seconds=1.0)
        admission = AdmissionService(store, bus, clock)

        a = admission.create_training_job(spec("job-a", pool="v5p-pool",
                                               max_chips=8, epochs=2))
        b = admission.create_training_job(spec("job-b", pool="v5e-pool",
                                               max_chips=4, epochs=2))
        clock.advance(2.0)

        # Each job landed only on its pool's scheduler and cluster.
        assert a in scheds["v5p-pool"].job_num_chips
        assert a not in scheds["v5e-pool"].job_num_chips
        assert b in scheds["v5e-pool"].job_num_chips
        assert b not in scheds["v5p-pool"].job_num_chips
        assert scheds["v5p-pool"].job_num_chips[a] == 8
        assert scheds["v5e-pool"].job_num_chips[b] == 4

        clock.advance(3600.0)
        assert a in backends["v5p-pool"].completed
        assert b in backends["v5e-pool"].completed
        assert store.get_job(a).status == JobStatus.COMPLETED
        assert store.get_job(b).status == JobStatus.COMPLETED

    def test_delete_routes_to_owning_pool(self):
        clock = VirtualClock(start=1753760000.0)
        store = JobStore()
        bus = EventBus()
        backends = {}
        scheds = {}
        for pool in ("p1", "p2"):
            be = FakeClusterBackend(clock, restart_overhead_seconds=5.0)
            be.add_host(f"{pool}-h0", 4, announce=False)
            backends[pool] = be
            scheds[pool] = Scheduler(pool, be, store,
                                     ResourceAllocator(store), clock,
                                     bus=bus, rate_limit_seconds=1.0)
        admission = AdmissionService(store, bus, clock)
        a = admission.create_training_job(spec("till-deleted", pool="p2",
                                               max_chips=4, epochs=1000))
        clock.advance(2.0)
        assert a in scheds["p2"].job_num_chips
        admission.delete_training_job(a)
        clock.advance(2.0)
        assert a not in scheds["p2"].job_num_chips
        assert not backends["p2"].running_jobs()
        assert a not in scheds["p1"].job_num_chips


class TestApplyFailureIsolation:
    """A backend raise during start/scale must not strand the job as
    phantom-running (found live in r5: one 503 during start_job left
    job_num_chips claiming chips the backend never realized, so the
    diff never re-emitted the start)."""

    class _FlakyStartBackend(FakeClusterBackend):
        def __init__(self, clock, fail_starts=1, **kw):
            super().__init__(clock, **kw)
            self.fail_starts = fail_starts
            self.start_attempts = 0

        def start_job(self, spec, num_workers, placements=None):
            self.start_attempts += 1
            if self.fail_starts > 0:
                self.fail_starts -= 1
                raise RuntimeError("injected 503")
            super().start_job(spec, num_workers, placements)

    def test_failed_start_reverts_and_retries(self):
        clock = VirtualClock(start=1753760000.0)
        backend = self._FlakyStartBackend(clock, fail_starts=1,
                                          restart_overhead_seconds=5.0)
        for i in range(2):
            backend.add_host(f"host-{i}", 4, announce=False)
        clock2, store, bus, backend, sched, admission = build_world(
            backend=backend, clock=clock)
        backend.register_profile("j", WorkloadProfile(epoch_seconds_at_1=30.0))
        name = admission.create_training_job(spec("j", max_chips=8, epochs=2))
        # First start failed: bookkeeping must NOT claim the allocation.
        assert sched.job_num_chips.get(name, 0) == 0
        assert store.get_job(name).status != JobStatus.RUNNING
        # The scheduled retry starts it for real.
        clock.advance(10.0)
        assert backend.start_attempts >= 2
        assert store.get_job(name).status == JobStatus.RUNNING
        assert sched.job_num_chips[name] == 8
        # And the job runs to completion as normal.
        clock.advance(3600.0)
        assert store.get_job(name).status == JobStatus.COMPLETED

    def test_other_jobs_survive_one_failed_start(self):
        clock = VirtualClock(start=1753760000.0)
        backend = self._FlakyStartBackend(clock, fail_starts=1,
                                          restart_overhead_seconds=5.0)
        for i in range(2):
            backend.add_host(f"host-{i}", 4, announce=False)
        _, store, bus, backend, sched, admission = build_world(
            backend=backend, clock=clock)
        for j in ("a", "b"):
            backend.register_profile(
                j, WorkloadProfile(epoch_seconds_at_1=30.0))
        # One job's failed start must not poison the other: both are
        # submitted while the storm eats the first attempt, and both
        # must still run to completion via the retry machinery.
        na = admission.create_training_job(spec("a", max_chips=4, epochs=2))
        nb = admission.create_training_job(spec("b", max_chips=4, epochs=2))
        clock.advance(10.0)
        statuses = {store.get_job(n).status for n in (na, nb)}
        assert JobStatus.FAILED not in statuses
        assert JobStatus.RUNNING in statuses
        clock.advance(3600.0)
        assert store.get_job(na).status == JobStatus.COMPLETED
        assert store.get_job(nb).status == JobStatus.COMPLETED

    class _FlakyStopBackend(FakeClusterBackend):
        def __init__(self, clock, fail_stops=1, **kw):
            super().__init__(clock, **kw)
            self.fail_stops = fail_stops

        def stop_job(self, name):
            if self.fail_stops > 0:
                self.fail_stops -= 1
                raise RuntimeError("injected stop 503")
            super().stop_job(name)

    def test_failed_halt_aborts_pass_no_double_booking(self):
        # SRJF preempts a long job for a short one. If the halt raises,
        # the short job's start was computed assuming the freed chips —
        # applying it would double-book hosts; the pass must stop and
        # the retry must do the whole swap cleanly.
        clock = VirtualClock(start=1753760000.0)
        backend = self._FlakyStopBackend(clock, fail_stops=1,
                                         restart_overhead_seconds=5.0)
        for i in range(2):
            backend.add_host(f"host-{i}", 4, announce=False)
        _, store, bus, backend, sched, admission = build_world(
            backend=backend, clock=clock, algorithm="SRJF")
        backend.register_profile(
            "long", WorkloadProfile(epoch_seconds_at_1=120.0))
        backend.register_profile(
            "short", WorkloadProfile(epoch_seconds_at_1=30.0))
        nl = admission.create_training_job(
            spec("long", max_chips=8, epochs=50))
        assert store.get_job(nl).status == JobStatus.RUNNING
        ns = admission.create_training_job(
            spec("short", max_chips=8, epochs=1))
        clock.advance(5.0)  # the pass with the failing halt
        booked = sum(sched.job_num_chips.values())
        assert booked <= sched.total_chips, sched.job_num_chips
        clock.advance(3600.0)
        assert store.get_job(ns).status == JobStatus.COMPLETED
        clock.advance(100000.0)
        assert store.get_job(nl).status == JobStatus.COMPLETED

    class _StormBackend(FakeClusterBackend):
        """scale_job AND running_jobs both fail while the storm is on."""

        def __init__(self, clock, storm_calls=1, **kw):
            super().__init__(clock, **kw)
            self.storm_calls = storm_calls

        def _storm(self):
            if self.storm_calls > 0:
                self.storm_calls -= 1
                raise RuntimeError("injected storm 503")

        def scale_job(self, name, num_workers, placements=None):
            self._storm()
            super().scale_job(name, num_workers, placements)

        def running_jobs(self):
            self._storm()
            return super().running_jobs()

    def test_storm_during_scale_keeps_old_booking_no_livelock(self):
        # scale_job raises AND the post-failure running_jobs() probe
        # raises too: the scheduler must keep the OLD booking (pods may
        # still hold the chips) instead of assuming not-running — the
        # wrong assumption double-books hosts and livelocks retried
        # starts against "already running". After the storm passes, the
        # shrink applies and both jobs complete.
        clock = VirtualClock(start=1753760000.0)
        backend = self._StormBackend(clock, storm_calls=2,
                                     restart_overhead_seconds=5.0)
        for i in range(2):
            backend.add_host(f"host-{i}", 4, announce=False)
        _, store, bus, backend, sched, admission = build_world(
            backend=backend, clock=clock)
        backend.register_profile(
            "a", WorkloadProfile(epoch_seconds_at_1=60.0))
        backend.register_profile(
            "b", WorkloadProfile(epoch_seconds_at_1=60.0))
        na = admission.create_training_job(spec("a", max_chips=8, epochs=20))
        assert sched.job_num_chips[na] == 8
        clock.advance(2.0)
        # b's admission triggers the shrink of a — which hits the storm.
        nb = admission.create_training_job(spec("b", max_chips=8, epochs=2))
        # a keeps its old 8-chip booking; b must NOT have started onto
        # a's hosts (the pass aborted before applying the start).
        assert sched.job_num_chips[na] == 8, sched.job_num_chips
        assert sched.job_num_chips.get(nb, 0) == 0, sched.job_num_chips
        assert sum(sched.job_num_chips.values()) <= sched.total_chips
        clock.advance(10.0)  # retry lands after the storm
        assert sched.job_num_chips[na] == 4
        assert sched.job_num_chips[nb] == 4
        clock.advance(100000.0)
        assert store.get_job(na).status == JobStatus.COMPLETED
        assert store.get_job(nb).status == JobStatus.COMPLETED
