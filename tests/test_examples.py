"""Examples layer: scripts run, resume elastically, and specs parse.

Covers the gap the reference left untested (SURVEY.md §4: its examples
are exercised only live) — here each example runs hermetically on a
virtual CPU mesh.
"""

import os
import signal
import subprocess
import sys
import time

import pytest
import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = os.path.join(REPO, "examples")


def _run_example(script, args, chips, timeout=240):
    env = dict(os.environ)
    env["VODA_FORCE_CPU_DEVICES"] = str(chips)
    env.pop("JAX_PLATFORMS", None)
    return subprocess.run(
        [sys.executable, os.path.join(EXAMPLES, "jax", script)] + args,
        env=env, cwd=REPO, capture_output=True, text=True, timeout=timeout)


@pytest.mark.slow
class TestMnistExample:
    def test_trains_then_resumes_at_new_chip_count(self, tmp_path):
        wd = str(tmp_path / "mnist")
        base = ["--workdir", wd, "--epochs", "1", "--steps-per-epoch", "4",
                "--batch-size", "16"]
        r = _run_example("mnist_mlp_elastic.py", base + ["--num-chips", "2"],
                         chips=2)
        assert r.returncode == 0, r.stderr
        assert "training complete" in r.stdout
        assert os.path.exists(os.path.join(wd, "ckpt"))
        csv = os.path.join(wd, "metrics", "mnist-mlp-elastic.csv")
        assert os.path.exists(csv)

        # Elastic restart: more epochs at a different chip count resumes
        # from the checkpoint instead of starting over.
        r2 = _run_example("mnist_mlp_elastic.py",
                          ["--workdir", wd, "--epochs", "2",
                           "--steps-per-epoch", "4", "--batch-size", "16",
                           "--num-chips", "4"], chips=4)
        assert r2.returncode == 0, r2.stderr
        assert "resumed at step 4" in r2.stdout


@pytest.mark.slow
class TestSyntheticBenchmark:
    def test_prints_throughput(self):
        r = _run_example("synthetic_benchmark.py",
                         ["--model", "mnist_mlp", "--num-chips", "2",
                          "--batch-size", "16", "--num-warmup-batches", "1",
                          "--num-batches-per-iter", "2", "--num-iters", "1"],
                         chips=2)
        assert r.returncode == 0, r.stderr
        assert "examples/sec on 2 chips" in r.stdout


@pytest.mark.slow
class TestTransformerExample:
    def test_explicit_plan(self, tmp_path):
        r = _run_example("transformer_lm_elastic.py",
                         ["--workdir", str(tmp_path / "lm"), "--epochs", "1",
                          "--steps-per-epoch", "2", "--batch-size", "4",
                          "--num-chips", "4", "--plan", "dp2,tp2"], chips=4)
        assert r.returncode == 0, r.stderr
        assert "'dp': 2" in r.stdout and "'tp': 2" in r.stdout

    def test_parse_plan(self):
        import importlib.util
        path = os.path.join(EXAMPLES, "jax", "transformer_lm_elastic.py")
        spec = importlib.util.spec_from_file_location("tx_example", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        parse_plan = mod.parse_plan
        plan = parse_plan("dp2,fsdp2,tp2")
        assert (plan.dp, plan.fsdp, plan.tp) == (2, 2, 2)
        assert parse_plan("auto") is None
        with pytest.raises(ValueError):
            parse_plan("xp3")
        with pytest.raises(ValueError):
            parse_plan("dp")


@pytest.mark.slow
class TestCustomScript:
    def test_supervisor_runs_user_script(self, tmp_path):
        """End-to-end: a job whose model comes from extra.script."""
        from vodascheduler_tpu.common.job import JobConfig, JobSpec
        from vodascheduler_tpu.runtime.supervisor import load_bundle

        spec = JobSpec(
            name="custom-cnn-test",
            config=JobConfig(min_num_chips=1, max_num_chips=2, epochs=1),
            model="custom", global_batch_size=8, steps_per_epoch=2,
            extra={"script": os.path.join(EXAMPLES, "jax",
                                          "custom_cnn_script.py"),
                   "width": "8"})
        bundle = load_bundle(spec)
        assert bundle.name == "custom_cnn"
        assert bundle.module.width == 8

        import json
        wd = tmp_path / "job"
        wd.mkdir()
        (wd / "spec.json").write_text(json.dumps(spec.to_dict()))
        env = dict(os.environ)
        env["VODA_FORCE_CPU_DEVICES"] = "2"
        r = subprocess.run(
            [sys.executable, "-m", "vodascheduler_tpu.runtime.supervisor",
             "--workdir", str(wd), "--num-chips", "2"],
            env=env, cwd=REPO, capture_output=True, text=True, timeout=240)
        assert r.returncode == 0, r.stderr
        assert (wd / "ckpt").exists()

    def test_missing_get_model_rejected(self, tmp_path):
        from vodascheduler_tpu.common.job import JobSpec
        from vodascheduler_tpu.runtime.supervisor import load_bundle

        bad = tmp_path / "bad.py"
        bad.write_text("x = 1\n")
        with pytest.raises(AttributeError):
            load_bundle(JobSpec(name="j", extra={"script": str(bad)}))


@pytest.mark.slow
class TestPreemption:
    def test_sigterm_checkpoints_and_exits_preempted(self, tmp_path):
        from vodascheduler_tpu.common.types import PREEMPTED_EXIT_CODE

        wd = str(tmp_path / "mnist")
        env = dict(os.environ)
        env["VODA_FORCE_CPU_DEVICES"] = "1"
        env.pop("JAX_PLATFORMS", None)
        env["PYTHONUNBUFFERED"] = "1"
        proc = subprocess.Popen(
            [sys.executable,
             os.path.join(EXAMPLES, "jax", "mnist_mlp_elastic.py"),
             "--workdir", wd, "--epochs", "50", "--steps-per-epoch", "200",
             "--batch-size", "16", "--num-chips", "1"],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        # Wait for the sentinel printed after the SIGTERM handler is
        # installed (so the signal preempts instead of killing), then stop.
        seen = []
        for line in proc.stdout:
            seen.append(line)
            if "elastic run:" in line:
                break
        assert proc.poll() is None, "".join(seen)
        time.sleep(1.0)  # let it enter run_steps
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=180)
        assert proc.returncode == PREEMPTED_EXIT_CODE, "".join(seen) + out
        assert "preempted" in out


class TestJobSpecYamls:
    def test_all_example_specs_parse(self):
        from vodascheduler_tpu.common.job import JobSpec

        found = []
        for sub in ("jobs", "test_jobs"):
            d = os.path.join(EXAMPLES, sub)
            for fn in sorted(os.listdir(d)):
                if fn.endswith(".yaml"):
                    with open(os.path.join(d, fn)) as f:
                        spec = JobSpec.from_dict(yaml.safe_load(f))
                    assert spec.config.min_num_chips <= spec.config.max_num_chips
                    found.append(fn)
        assert len(found) >= 6
