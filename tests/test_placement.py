"""Placement-manager tests: best-fit consolidation, Hungarian stay-put
binding, tail-first release, migration diffing, ICI contiguity.

The reference had no placement tests (SURVEY.md §4); scenarios here pin the
documented semantics of placement_manager.go.
"""

import pytest

from vodascheduler_tpu.placement import (
    HostState,
    PlacementManager,
    PoolTopology,
    SliceShape,
)
from vodascheduler_tpu.placement.hungarian import solve_max, _solve_min
from vodascheduler_tpu.placement.topology import (
    default_pool,
    feasible_shapes,
    next_feasible_above,
    round_to_feasible,
)


class TestHungarian:
    def test_identity(self):
        score = [[1, 0], [0, 1]]
        assert sorted(solve_max(score)) == [(0, 0), (1, 1)]

    def test_max_assignment(self):
        score = [[10, 2, 3], [4, 50, 6], [7, 8, 9]]
        pairs = dict(solve_max(score))
        assert pairs == {0: 0, 1: 1, 2: 2}

    def test_forced_off_diagonal(self):
        score = [[0, 10], [10, 0]]
        pairs = dict(solve_max(score))
        assert pairs == {0: 1, 1: 0}

    def test_against_bruteforce(self):
        import itertools
        import random

        rng = random.Random(42)
        for n in (1, 2, 3, 4, 5):
            for _ in range(20):
                score = [[rng.randint(0, 20) for _ in range(n)] for _ in range(n)]
                got = sum(score[r][c] for r, c in solve_max(score))
                best = max(sum(score[i][p[i]] for i in range(n))
                           for p in itertools.permutations(range(n)))
                assert got == best

    def test_empty(self):
        assert solve_max([]) == []


class TestTopology:
    def test_feasible_shapes_prefers_compact(self):
        shapes = feasible_shapes(8, (4, 4, 4))
        assert shapes[0].dims == (2, 2, 2)
        assert all(s.num_chips == 8 for s in shapes)

    def test_infeasible_count(self):
        # 5 chips never tiles a 4x4x4 torus (5 doesn't divide into axes <= 4)
        assert feasible_shapes(5, (4, 4, 4)) == []
        topo = PoolTopology(torus_dims=(4, 4, 4), host_block=(2, 2, 1))
        assert round_to_feasible(5, topo) == 4

    def test_rounding_respects_host_granularity(self):
        # Above one host (4 chips), counts snap to whole-host sub-tori.
        topo = PoolTopology(torus_dims=(4, 4, 4), host_block=(2, 2, 1))
        assert round_to_feasible(7, topo) == 4
        assert round_to_feasible(16, topo) == 16
        # 24 chips = 6 hosts = a 1x2x3 box on the (2,2,4) host grid
        assert next_feasible_above(16, topo) == 24

    def test_host_grid_and_distance(self):
        topo = PoolTopology(torus_dims=(4, 4, 4), host_block=(2, 2, 1))
        assert topo.chips_per_host == 4
        assert topo.host_grid == (2, 2, 4)
        assert topo.num_hosts == 16
        # wraparound: coords 0 and 3 on a 4-long axis are 1 hop apart
        assert topo.host_distance((0, 0, 0), (0, 0, 3)) == 1
        assert topo.host_distance((0, 0, 0), (1, 1, 2)) == 4

    def test_slice_shape_parse(self):
        assert SliceShape.parse("2x2x1").num_chips == 4
        assert str(SliceShape((4, 4))) == "4x4"

    def test_bad_host_block(self):
        with pytest.raises(ValueError):
            PoolTopology(torus_dims=(4, 4, 4), host_block=(3, 1, 1))


def manager_with_hosts(num_hosts: int = 4, chips: int = 4) -> PlacementManager:
    pm = PlacementManager("test-pool")
    for i in range(num_hosts):
        pm.add_host(f"host-{i}", chips)
    return pm


class TestPlacementManager:
    def test_single_job_consolidates_on_one_host(self):
        pm = manager_with_hosts(4, 4)
        decision = pm.place({"a": 4})
        assert len(decision.placements["a"]) == 1
        assert decision.num_jobs_cross_host == 0

    def test_best_fit_prefers_tightest_host(self):
        pm = PlacementManager("test-pool")
        pm.add_host("big", 8)
        pm.add_host("small", 2)
        decision = pm.place({"a": 2})
        # best-fit = fewest free slots that still fit -> "small"
        assert decision.placements["a"] == [("small", 2)]

    def test_spill_across_hosts_counts_cross_host(self):
        pm = manager_with_hosts(2, 4)
        decision = pm.place({"a": 6})
        assert sum(n for _, n in decision.placements["a"]) == 6
        assert decision.num_jobs_cross_host == 1

    def test_stay_put_on_rebalance(self):
        pm = manager_with_hosts(2, 4)
        d1 = pm.place({"a": 4})
        host_a = d1.placements["a"][0][0]
        # Add another job; a must not migrate.
        d2 = pm.place({"a": 4, "b": 4})
        assert d2.placements["a"] == [(host_a, 4)]
        assert "a" not in d2.migrations
        assert d2.workers_migrated == 0

    def test_scale_down_releases_tail(self):
        pm = manager_with_hosts(3, 4)
        pm.place({"a": 10})
        d = pm.place({"a": 4})
        # 4 workers remain; tail hosts released; surviving workers stay put.
        assert sum(n for _, n in d.placements["a"]) == 4
        assert "a" not in d.migrations

    def test_scale_up_no_migration_of_existing(self):
        pm = manager_with_hosts(3, 4)
        pm.place({"a": 4})
        d = pm.place({"a": 8})
        assert sum(n for _, n in d.placements["a"]) == 8
        assert "a" not in d.migrations  # old workers kept their hosts

    def test_termination_releases_everything(self):
        pm = manager_with_hosts(2, 4)
        pm.place({"a": 8})
        pm.place({})
        assert pm.job_placements == {}
        assert all(h.free_slots == h.total_slots
                   for h in pm.host_states.values())

    def test_migration_detected_on_forced_move(self):
        pm = manager_with_hosts(2, 4)
        pm.place({"a": 2, "b": 2})  # both jobs fit, each on some host
        # b grows to need a full host; consolidation may move someone.
        d = pm.place({"a": 4, "b": 4})
        # whatever happened, final state is consistent:
        assert sum(n for _, n in d.placements["a"]) == 4
        assert sum(n for _, n in d.placements["b"]) == 4
        for job, moved in d.migrations.items():
            assert moved  # no empty migration entries

    def test_host_removal_zeroes_job_and_next_place_recovers(self):
        pm = manager_with_hosts(3, 4)
        d1 = pm.place({"a": 4})
        victim = d1.placements["a"][0][0]
        pm.remove_host(victim)
        d2 = pm.place({"a": 4})
        assert sum(n for _, n in d2.placements["a"]) == 4
        assert victim not in [h for h, _ in d2.placements["a"]]

    def test_overcommit_places_what_fits(self):
        pm = manager_with_hosts(1, 4)
        d = pm.place({"a": 4, "b": 4})
        placed = sum(n for p in d.placements.values() for _, n in p)
        assert placed == 4  # tolerated inconsistency, no crash

    def test_restore_reconstructs_state(self):
        pm = manager_with_hosts(2, 4)
        pm.restore({"a": [("host-0", 4), ("host-1", 2)]})
        assert pm.job_placements["a"].num_workers == 6
        assert pm.host_states["host-0"].free_slots == 0
        assert pm.host_states["host-1"].free_slots == 2
        # subsequent place keeps workers put
        d = pm.place({"a": 6})
        assert "a" not in d.migrations


class TestICIContiguity:
    def test_multi_host_job_lands_on_adjacent_hosts(self):
        topo = PoolTopology(torus_dims=(8, 2, 2), host_block=(2, 2, 2))
        pm = PlacementManager("v5p-pool")
        pm.add_hosts_from_topology(topo)
        assert pm.total_chips == 32
        # 16-chip job = 2 hosts: they must be torus neighbors.
        d = pm.place({"a": 16})
        hosts = [h for h, _ in d.placements["a"]]
        assert len(hosts) == 2
        coords = [pm.host_states[h].coord for h in hosts]
        assert topo.host_distance(coords[0], coords[1]) == 1
        assert d.total_contiguity_cost == 1

    def test_two_jobs_partition_the_ring(self):
        topo = default_pool(num_hosts=4, chips_per_host=4)
        pm = PlacementManager("pool")
        pm.add_hosts_from_topology(topo)
        d = pm.place({"a": 8, "b": 8})
        a_hosts = {h for h, _ in d.placements["a"]}
        b_hosts = {h for h, _ in d.placements["b"]}
        assert not (a_hosts & b_hosts)
        assert len(a_hosts) == 2 and len(b_hosts) == 2


class TestDefragment:
    def test_defragment_consolidates_fragmented_job(self):
        pm = manager_with_hosts(3, 4)
        # fragment: a spans two hosts after churn
        pm.place({"a": 2, "b": 4, "c": 4})
        pm.place({"a": 6, "b": 4})  # c gone; a grows into freed space
        frag = {h for h, n in ((hs.host, hs.num_slots)
                for hs in pm.job_placements["a"].host_slots) if n > 0}
        d = pm.defragment({"a": 6, "b": 4})
        assert sum(n for _, n in d.placements["a"]) == 6
        assert sum(n for _, n in d.placements["b"]) == 4

    def test_scheduler_triggers_defrag_at_threshold(self):
        from tests.test_scheduler import build_world, spec

        clock, store, bus, backend, sched, admission = build_world(
            num_hosts=4, chips_per_host=4)
        sched.defrag_cross_host_threshold = 1
        a = admission.create_training_job(spec("a", min_chips=1, max_chips=6))
        clock.advance(2.0)
        b = admission.create_training_job(spec("b", min_chips=1, max_chips=6))
        clock.advance(2.0)
        # a=6 spans hosts -> cross_host >= 1 -> next pass defragments
        assert sched._last_cross_host >= 1
        admission.create_training_job(spec("c", min_chips=1, max_chips=4))
        clock.advance(5.0)  # this resched runs defragment() without error
        placed = sum(sum(n for _, n in p)
                     for p in sched.placement_manager.job_placements and
                     [[(hs.host, hs.num_slots) for hs in jp.host_slots]
                      for jp in sched.placement_manager.job_placements.values()])
        assert placed == sum(sched.job_num_chips.values())


class TestFeasibilityRounding:
    """round_to_feasible / next_feasible_above — the slice-shape feasibility
    vocabulary on the allocation path (VERDICT r1 item 3)."""

    def setup_method(self):
        from vodascheduler_tpu.placement.topology import PoolTopology
        self.topo = PoolTopology(torus_dims=(4, 4, 4), host_block=(2, 2, 1))

    def test_sub_host_counts_round_within_host_block(self):
        from vodascheduler_tpu.placement.topology import round_to_feasible
        # host block 2x2x1: 1, 2, 4 feasible; 3 rounds to 2
        assert round_to_feasible(1, self.topo) == 1
        assert round_to_feasible(2, self.topo) == 2
        assert round_to_feasible(3, self.topo) == 2

    def test_multi_host_counts_whole_host_subtorus(self):
        from vodascheduler_tpu.placement.topology import round_to_feasible
        assert round_to_feasible(4, self.topo) == 4
        assert round_to_feasible(5, self.topo) == 4   # the VERDICT example
        assert round_to_feasible(7, self.topo) == 4
        assert round_to_feasible(8, self.topo) == 8
        assert round_to_feasible(64, self.topo) == 64

    def test_next_feasible_above(self):
        from vodascheduler_tpu.placement.topology import next_feasible_above
        assert next_feasible_above(2, self.topo) == 4
        assert next_feasible_above(4, self.topo) == 8
        assert next_feasible_above(64, self.topo) is None

    def test_is_feasible_count(self):
        from vodascheduler_tpu.placement.topology import is_feasible_count
        assert is_feasible_count(0, self.topo)
        assert is_feasible_count(8, self.topo)
        assert not is_feasible_count(5, self.topo)
