"""Unit tests for the 8 scheduling algorithms.

The reference shipped zero algorithm tests (SURVEY.md §4); behavior here is
pinned against the reference's documented semantics (pkg/algorithm/*.go).
"""

import math

import pytest

from tests.helpers import make_job
from vodascheduler_tpu.algorithms import (
    ALGORITHM_NAMES,
    AFSL,
    ElasticFIFO,
    ElasticSRJF,
    ElasticTiresias,
    FIFO,
    FfDLOptimizer,
    InvalidAllocationError,
    SRJF,
    Tiresias,
    new_algorithm,
    validate_result,
)
from vodascheduler_tpu.algorithms.tiresias import (
    TIRESIAS_THRESHOLDS_SEC,
    tiresias_demote_priority,
    tiresias_promote_priority,
)


class TestFactory:
    def test_all_names_resolve(self):
        for name in ALGORITHM_NAMES:
            algo = new_algorithm(name, "sched-test")
            assert algo.name == name

    def test_unknown_name(self):
        with pytest.raises(ValueError):
            new_algorithm("NotAnAlgorithm")

    def test_needs_job_info_flags(self):
        # Reference: NeedJobInfo per algorithm file.
        expect = {
            "FIFO": False, "ElasticFIFO": False,
            "SRJF": True, "ElasticSRJF": True,
            "Tiresias": False, "ElasticTiresias": True,
            "FfDLOptimizer": True, "AFS-L": True,
        }
        for name, flag in expect.items():
            assert new_algorithm(name).needs_job_info is flag


class TestValidateResult:
    def test_rejects_negative(self):
        jobs = [make_job("a")]
        with pytest.raises(InvalidAllocationError):
            validate_result(4, {"a": -1}, jobs)

    def test_rejects_below_min(self):
        jobs = [make_job("a", min_chips=2, max_chips=4)]
        with pytest.raises(InvalidAllocationError):
            validate_result(4, {"a": 1}, jobs)

    def test_rejects_above_max(self):
        jobs = [make_job("a", min_chips=1, max_chips=2)]
        with pytest.raises(InvalidAllocationError):
            validate_result(4, {"a": 3}, jobs)

    def test_rejects_oversubscription(self):
        jobs = [make_job("a", max_chips=8), make_job("b", max_chips=8)]
        with pytest.raises(InvalidAllocationError):
            validate_result(4, {"a": 4, "b": 4}, jobs[:1] + jobs[1:])

    def test_accepts_zero_and_valid(self):
        jobs = [make_job("a", min_chips=2, max_chips=4)]
        validate_result(4, {"a": 0}, jobs)
        validate_result(4, {"a": 3}, jobs)


class TestFIFO:
    def test_submit_order_min_allocation(self):
        jobs = [make_job("b", submit_time=2, min_chips=2),
                make_job("a", submit_time=1, min_chips=3)]
        result = FIFO().schedule(jobs, 4)
        # a first (earlier submit) gets min=3; b's min=2 > 1 left -> 0.
        assert result == {"a": 3, "b": 0}

    def test_non_elastic_never_exceeds_min(self):
        jobs = [make_job("a", min_chips=1, max_chips=8)]
        assert FIFO().schedule(jobs, 8) == {"a": 1}

    def test_empty(self):
        assert FIFO().schedule([], 8) == {}


class TestElasticFIFO:
    def test_leftover_round_robin(self):
        jobs = [make_job("a", submit_time=1, min_chips=1, max_chips=3),
                make_job("b", submit_time=2, min_chips=1, max_chips=3)]
        result = ElasticFIFO().schedule(jobs, 5)
        # mins: a=1,b=1; leftovers 3 round-robin in submit order: a,b,a.
        assert result == {"a": 3, "b": 2}

    def test_capped_at_max(self):
        jobs = [make_job("a", min_chips=1, max_chips=2)]
        assert ElasticFIFO().schedule(jobs, 8) == {"a": 2}

    def test_zero_allocated_job_stays_zero(self):
        # The reference panics on this shape (see base.distribute_leftover);
        # we keep B at 0 rather than giving it a sub-minimum share.
        jobs = [make_job("a", submit_time=1, min_chips=1, max_chips=10),
                make_job("b", submit_time=2, min_chips=3, max_chips=3)]
        result = ElasticFIFO().schedule(jobs, 3)
        assert result == {"a": 3, "b": 0}


class TestSRJF:
    def test_shortest_remaining_first(self):
        jobs = [make_job("long", remaining=1000, min_chips=2),
                make_job("short", remaining=10, min_chips=2)]
        result = SRJF().schedule(jobs, 3)
        assert result == {"short": 2, "long": 0}


class TestElasticSRJF:
    def test_leftover_to_shortest_first(self):
        jobs = [make_job("long", remaining=1000, min_chips=1, max_chips=4),
                make_job("short", remaining=10, min_chips=1, max_chips=4)]
        result = ElasticSRJF().schedule(jobs, 6)
        # mins 1+1, leftover 4 round-robins short,long,short,long.
        assert result == {"short": 3, "long": 3}


class TestTiresias:
    def test_priority_queues_then_start_time(self):
        jobs = [
            make_job("low", num_chips=2, min_chips=2, max_chips=4, priority=1,
                     first_start_time=1.0),
            make_job("hi-late", num_chips=2, min_chips=2, max_chips=4, priority=0,
                     first_start_time=5.0),
            make_job("hi-early", num_chips=2, min_chips=2, max_chips=4, priority=0,
                     first_start_time=2.0),
        ]
        result = Tiresias().schedule(jobs, 4)
        # Queue 0 first, FIFO by first start time: hi-early, hi-late.
        assert result == {"hi-early": 2, "hi-late": 2, "low": 0}

    def test_allocates_fixed_num_proc(self):
        jobs = [make_job("a", num_chips=3, min_chips=1, max_chips=8)]
        assert Tiresias().schedule(jobs, 8) == {"a": 3}

    def test_never_started_sorts_last(self):
        jobs = [make_job("started", num_chips=2, min_chips=2, first_start_time=1.0,
                         max_chips=4),
                make_job("fresh", num_chips=2, min_chips=2, max_chips=4)]
        result = Tiresias().schedule(jobs, 2)
        assert result == {"started": 2, "fresh": 0}

    def test_demote_promote_helpers(self):
        assert tiresias_demote_priority(0) == 1
        assert tiresias_demote_priority(1) == 1  # bottom queue stays
        assert tiresias_promote_priority(1) == 0
        assert TIRESIAS_THRESHOLDS_SEC[0] == 3600.0
        assert math.isinf(TIRESIAS_THRESHOLDS_SEC[1])


class TestElasticTiresias:
    def test_leftover_goes_to_max_marginal_gain(self):
        # diminishing returns for a, linear for b -> extra chips go to b.
        jobs = [
            make_job("a", num_chips=1, min_chips=1, max_chips=4,
                     speedup={0: 0, 1: 1.0, 2: 1.1, 3: 1.15, 4: 1.18, 5: 1.2},
                     first_start_time=1.0),
            make_job("b", num_chips=1, min_chips=1, max_chips=4,
                     speedup={0: 0, 1: 1.0, 2: 2.0, 3: 3.0, 4: 4.0, 5: 5.0},
                     first_start_time=2.0),
        ]
        result = ElasticTiresias().schedule(jobs, 6)
        assert result == {"a": 2, "b": 4}

    def test_no_gain_no_allocation(self):
        # Zero-marginal-gain growth is declined: a grant is a
        # checkpoint-restart, so flat speedup regions aren't worth it.
        jobs = [make_job("a", num_chips=1, min_chips=1, max_chips=8,
                         speedup={0: 0, 1: 1.0, 2: 1.0, 3: 1.0, 4: 1.0})]
        result = ElasticTiresias().schedule(jobs, 8)
        assert result == {"a": 1}

    def test_compaction_shrinks_low_priority(self):
        # A low-priority job holding 4 chips + 12 pending jobs too big to
        # ever start (min 8 > capacity 6): the deep pending backlog (>10)
        # triggers compaction, shrinking the fat job to its min. Flat
        # speedup keeps the greedy phase from re-growing it.
        fat = make_job("fat", num_chips=4, min_chips=1, max_chips=4, priority=1,
                       first_start_time=1.0,
                       speedup={n: 1.0 if n else 0.0 for n in range(0, 9)})
        pendings = [make_job(f"p{i}", num_chips=8, min_chips=8, max_chips=8,
                             speedup={n: float(n) for n in range(0, 10)})
                    for i in range(12)]
        result = ElasticTiresias().schedule([fat] + pendings, 6)
        # compaction shrank fat to min=1; its flat curve (zero gain) means
        # regrowing it isn't worth a restart, and no pending job fits
        # (min 8 > capacity 6)
        assert result["fat"] == 1
        assert all(result[f"p{i}"] == 0 for i in range(12))

    def test_running_job_absorbs_leftover_below_its_min(self):
        # The reference's candidate filter would strand the last chip
        # (free=1 < min=2) even though the job is already running.
        jobs = [make_job("run", num_chips=2, min_chips=2, max_chips=4,
                         first_start_time=1.0,
                         speedup={n: float(n) for n in range(10)})]
        assert ElasticTiresias().schedule(jobs, 3) == {"run": 3}

    def test_floor_lift_rescues_long_starved_job(self):
        """r4 tail guard: a job stuck at its floor past
        FLOOR_LIFT_AGE_SECONDS outbids a better-gain young job for the
        leftover chip; the boost vanishes once it is off the floor, so
        lifted jobs cannot hoard."""
        from vodascheduler_tpu.common.types import JobStatus

        def running(name, running_seconds, speedup):
            j = make_job(name, num_chips=1, min_chips=1, max_chips=4,
                         speedup=speedup, first_start_time=1.0,
                         status=JobStatus.RUNNING)
            j.metrics.running_seconds = running_seconds
            return j

        young = running("young", 100.0,
                        {0: 0, 1: 1.0, 2: 1.9, 3: 2.7, 4: 3.4})  # gain .9
        old = running("old", 5000.0,
                      {0: 0, 1: 1.0, 2: 1.6, 3: 1.7, 4: 1.75})   # gain .6
        # One leftover chip: raw gain prefers young (0.9 > 0.6), but the
        # floor lift doubles old's bid (1.2) — old gets off the floor.
        assert ElasticTiresias().schedule([young, old], 3) == {
            "young": 1, "old": 2}
        # Same shape, old not yet past the lift age: young wins.
        old_fresh = running("old", 100.0,
                            {0: 0, 1: 1.0, 2: 1.6, 3: 1.7, 4: 1.75})
        assert ElasticTiresias().schedule([young, old_fresh], 3) == {
            "young": 2, "old": 1}
        # Two leftovers: old takes ONE (off the floor), then competes
        # unboosted (gain 0.1 < 0.9) — young takes the second. No hoard.
        old2 = running("old", 5000.0,
                       {0: 0, 1: 1.0, 2: 1.6, 3: 1.7, 4: 1.75})
        assert ElasticTiresias().schedule([young, old2], 4) == {
            "young": 2, "old": 2}

    def test_pending_job_needs_full_min(self):
        jobs = [
            make_job("running", num_chips=1, min_chips=1, max_chips=2,
                     speedup={0: 0, 1: 1.0, 2: 1.2, 3: 1.2}, first_start_time=1.0),
            make_job("pending", num_chips=4, min_chips=4, max_chips=8,
                     speedup={n: float(n) for n in range(0, 10)}),
        ]
        # capacity 3: pending can't start (min 4 > 3 free after running=1)
        result = ElasticTiresias().schedule(jobs, 3)
        assert result["pending"] == 0
        assert result["running"] == 2


class TestFfDLOptimizer:
    def test_maximizes_total_speedup(self):
        jobs = [
            make_job("lin", submit_time=1, min_chips=1, max_chips=4,
                     speedup={0: 0, 1: 1.0, 2: 2.0, 3: 3.0, 4: 4.0}),
            make_job("flat", submit_time=2, min_chips=1, max_chips=4,
                     speedup={0: 0, 1: 1.2, 2: 1.25, 3: 1.28, 4: 1.3}),
        ]
        result = FfDLOptimizer().schedule(jobs, 4)
        # lin=3 + flat=1 -> 3 + 1.2 = 4.2 beats lin=4 alone (4.0).
        assert result == {"lin": 3, "flat": 1}

    def test_respects_min(self):
        jobs = [make_job("a", min_chips=4, max_chips=8,
                         speedup={n: float(n) for n in range(0, 10)})]
        assert FfDLOptimizer().schedule(jobs, 3) == {"a": 0}
        assert FfDLOptimizer().schedule(jobs, 4) == {"a": 4}

    def test_deep_queue_does_not_crash(self):
        # Reference panics "infeasible" when the FIFO-trimmed queue cannot
        # all be placed; our g=0 transition handles it.
        jobs = [make_job(f"j{i}", submit_time=i, min_chips=2, max_chips=4,
                         speedup={n: float(n) for n in range(0, 6)})
                for i in range(8)]
        result = FfDLOptimizer().schedule(jobs, 4)
        assert sum(result.values()) == 4

    def test_empty(self):
        assert FfDLOptimizer().schedule([], 4) == {}


class TestAFSL:
    def test_short_job_wins_when_unscheduled(self):
        jobs = [make_job("long", submit_time=1, remaining=1000, max_chips=2,
                         speedup={0: 0, 1: 1.0, 2: 1.5, 3: 1.7}),
                make_job("short", submit_time=2, remaining=10, max_chips=2,
                         speedup={0: 0, 1: 1.0, 2: 1.5, 3: 1.7})]
        result = AFSL().schedule(jobs, 1)
        assert result == {"short": 1, "long": 0}

    def test_all_chips_distributed_up_to_max(self):
        jobs = [make_job("a", remaining=100, max_chips=2,
                         speedup={0: 0, 1: 1, 2: 1.9, 3: 2.5}),
                make_job("b", remaining=200, max_chips=2,
                         speedup={0: 0, 1: 1, 2: 1.9, 3: 2.5})]
        result = AFSL().schedule(jobs, 4)
        assert result == {"a": 2, "b": 2}

    def test_reverted_chips_are_reauctioned(self):
        # b's sub-min partial win reverts to 0; its chips must go back to a
        # rather than sit idle.
        jobs = [make_job("a", remaining=10, min_chips=1, max_chips=8,
                         speedup={n: float(n) for n in range(10)}),
                make_job("b", remaining=20, min_chips=4, max_chips=4,
                         speedup={n: float(n) for n in range(10)})]
        result = AFSL().schedule(jobs, 5)
        assert sum(result.values()) == 5

    def test_sub_min_reverts_to_zero(self):
        jobs = [make_job("a", remaining=10, min_chips=1, max_chips=8,
                         speedup={n: float(n) for n in range(0, 10)}),
                make_job("b", remaining=20, min_chips=4, max_chips=4,
                         speedup={n: float(n) for n in range(0, 10)})]
        result = AFSL().schedule(jobs, 5)
        assert result["a"] >= 1
        assert result["b"] in (0, 4)


@pytest.mark.parametrize("name", ALGORITHM_NAMES)
@pytest.mark.parametrize("capacity", [0, 1, 3, 8, 64])
def test_all_algorithms_produce_valid_allocations(name, capacity):
    """Property test: every algorithm output validates on a mixed queue."""
    jobs = [
        make_job("a", submit_time=1, min_chips=1, max_chips=4, remaining=50,
                 first_start_time=1.0),
        make_job("b", submit_time=2, min_chips=2, max_chips=2, remaining=500,
                 priority=1, first_start_time=2.0),
        make_job("c", submit_time=3, min_chips=2, max_chips=8, remaining=5),
        make_job("d", submit_time=4, min_chips=1, max_chips=1, remaining=100,
                 first_start_time=3.0),
    ]
    result = new_algorithm(name).schedule(jobs, capacity)
    validate_result(capacity, result, jobs)
    assert set(result) == {"a", "b", "c", "d"}


class TestElasticTiresiasLease:
    """The TPU lease delta (elastic_tiresias.py LEASE_SECONDS): a running
    job inside its lease keeps >= min ahead of normal queue order, because
    every preemption is a checkpoint-restart."""

    def test_recently_started_job_keeps_min_over_new_arrival(self):
        from vodascheduler_tpu.common.types import JobStatus

        # b is running, demoted to queue 1 (high chip time overall), but
        # (re)started only 60s ago; a is a fresh queue-0 arrival. Without
        # the lease, a (queue 0) would take the only 2 chips and evict b.
        a = make_job("a", num_chips=2, min_chips=2, max_chips=2,
                     first_start_time=5000.0)
        b = make_job("b", num_chips=2, min_chips=2, max_chips=2,
                     first_start_time=1.0, status=JobStatus.RUNNING)
        b.metrics.chip_seconds = 10 * 3600.0   # queue-1 demotion territory
        b.metrics.last_chip_seconds = 2 * 3600.0
        b.priority = 1
        b.metrics.seconds_since_restart = 60.0  # just restarted
        result = ElasticTiresias().schedule([a, b], total_chips=2)
        assert result == {"a": 0, "b": 2}

    def test_lease_expired_job_yields_to_higher_queue(self):
        from vodascheduler_tpu.algorithms import elastic_tiresias as et
        from vodascheduler_tpu.common.types import JobStatus

        a = make_job("a", num_chips=2, min_chips=2, max_chips=2,
                     first_start_time=5000.0)
        b = make_job("b", num_chips=2, min_chips=2, max_chips=2,
                     first_start_time=1.0, status=JobStatus.RUNNING)
        b.priority = 1
        b.metrics.seconds_since_restart = et.LEASE_SECONDS + 1.0
        result = ElasticTiresias().schedule([a, b], total_chips=2)
        assert result == {"a": 2, "b": 0}
