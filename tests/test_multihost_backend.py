"""MultiHostBackend e2e: real multi-process jobs glued by a backend-issued
jax.distributed coordinator (VERDICT r1 item 2 — the reference's hostfile/
discovery-script machinery, scheduler.go:1074-1112, rebuilt TPU-native).

Each virtual host is a separate OS process with its own 2-device CPU
platform; a 2-host job therefore exercises the genuine multi-controller
path: coordinator handshake, cross-process GSPMD collectives, distributed
orbax save/restore, and process-set restart on resize.
"""

import os
import time

import pytest

from vodascheduler_tpu.cluster.backend import ClusterEventKind
from vodascheduler_tpu.cluster.multihost import MultiHostBackend
from vodascheduler_tpu.common.job import JobConfig, JobSpec
from vodascheduler_tpu.metricscollector.csv_logger import read_epoch_csv
from vodascheduler_tpu.runtime.checkpoint import latest_step

TIMEOUT = 240.0

pytestmark = pytest.mark.slow


def _wait(predicate, timeout=TIMEOUT, interval=0.2):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def _logs(tmp_path, job):
    out = []
    d = tmp_path / job
    if d.is_dir():
        for f in sorted(d.glob("supervisor_p*.log")):
            out.append(f"--- {f.name} ---\n" + f.read_text())
    return "\n".join(out)


def _spec(name, epochs=2, steps=3, min_chips=1, max_chips=4, pool="default"):
    return JobSpec(name=name, model="mnist_mlp", global_batch_size=8,
                   steps_per_epoch=steps, pool=pool,
                   config=JobConfig(min_num_chips=min_chips,
                                    max_num_chips=max_chips, epochs=epochs))


@pytest.fixture
def backend(tmp_path):
    b = MultiHostBackend(str(tmp_path), num_hosts=2, chips_per_host=2,
                         stop_grace_seconds=60.0)
    yield b
    b.close()


def test_two_process_job_completes(backend, tmp_path):
    events = []
    backend.set_event_callback(events.append)
    backend.start_job(_spec("job-mh"), num_workers=4,
                      placements=[("host-0", 2), ("host-1", 2)])
    handle = backend.running_jobs()["job-mh"]
    assert handle.placements == [("host-0", 2), ("host-1", 2)]

    assert _wait(lambda: any(e.kind == ClusterEventKind.JOB_COMPLETED
                             for e in events)), _logs(tmp_path, "job-mh")
    # One CSV writer (process 0) despite two processes; global workers=4.
    rows = read_epoch_csv(os.path.join(backend.metrics_dir, "job-mh.csv"))
    assert [int(r["epoch"]) for r in rows] == [0, 1]
    assert all(int(r["workers"]) == 4 for r in rows)
    assert latest_step(str(tmp_path / "job-mh" / "ckpt")) == 6


def test_resize_across_process_counts(backend, tmp_path):
    """1-process/2-chip -> 2-process/4-chip resize: the distributed restore
    reshards the single-process checkpoint onto the global mesh."""
    events = []
    backend.set_event_callback(events.append)
    backend.start_job(_spec("job-rs", epochs=25, steps=10), num_workers=2,
                      placements=[("host-0", 2)])
    ckpt_dir = str(tmp_path / "job-rs" / "ckpt")
    assert _wait(lambda: latest_step(ckpt_dir) is not None), \
        _logs(tmp_path, "job-rs")
    saved = latest_step(ckpt_dir)

    backend.scale_job("job-rs", 4,
                      placements=[("host-0", 2), ("host-1", 2)])
    assert _wait(lambda: any(e.kind == ClusterEventKind.JOB_COMPLETED
                             for e in events)), _logs(tmp_path, "job-rs")
    assert latest_step(ckpt_dir) == 250  # 25 epochs x 10 steps, no loss
    rows = read_epoch_csv(os.path.join(backend.metrics_dir, "job-rs.csv"))
    workers = [int(r["workers"]) for r in rows]
    assert workers[0] == 2 and workers[-1] == 4, workers
    assert saved >= 1


def test_host_removal_stops_resident_jobs(backend, tmp_path):
    events = []
    backend.set_event_callback(events.append)
    backend.start_job(_spec("job-hr", epochs=50, steps=5), num_workers=4,
                      placements=[("host-0", 2), ("host-1", 2)])
    ckpt_dir = str(tmp_path / "job-hr" / "ckpt")
    assert _wait(lambda: latest_step(ckpt_dir) is not None), \
        _logs(tmp_path, "job-hr")
    backend.remove_host("host-1")
    assert "job-hr" not in backend.running_jobs()
    assert any(e.kind == ClusterEventKind.HOST_REMOVED for e in events)
    # No failure event: the stop checkpointed and the job can restart.
    assert not any(e.kind == ClusterEventKind.JOB_FAILED for e in events)
    assert backend.list_hosts() == {"host-0": 2}


def test_scheduler_drives_multihost_elastic_share(tmp_path):
    """The VERDICT r1 scenario: a 2-process x 2-device job goes through
    start -> scale-down (contention) -> scale-back-up -> resume -> complete
    under the real scheduler with the real coordinator-issuing backend."""
    from tests.test_scheduler import build_world
    from vodascheduler_tpu.common.clock import Clock
    from vodascheduler_tpu.common.types import JobStatus

    backend = MultiHostBackend(str(tmp_path), num_hosts=2, chips_per_host=2,
                               stop_grace_seconds=60.0)
    clock, store, bus, _, sched, admission = build_world(
        backend=backend, clock=Clock(), rate_limit=0.3)
    try:
        big = admission.create_training_job(
            _spec("big", epochs=6, steps=5, min_chips=2, max_chips=4,
                  pool="pool"))
        sched.pump()

        def pump_until(pred, timeout=TIMEOUT):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                sched.pump()
                sched.update_time_metrics()
                if pred():
                    return True
                time.sleep(0.2)
            return False

        # Elastic start: alone in the pool, big gets all 4 chips (2 procs).
        assert pump_until(
            lambda: backend.running_jobs().get(big) is not None
            and backend.running_jobs()[big].num_workers == 4), \
            _logs(tmp_path, big)
        ckpt_dir = str(tmp_path / big / "ckpt")
        assert _wait(lambda: latest_step(ckpt_dir) is not None), \
            _logs(tmp_path, big)

        # Contention: a second job forces big down to 2 chips.
        small = admission.create_training_job(
            _spec("small", epochs=1, steps=2, min_chips=2, max_chips=2,
                  pool="pool"))
        assert pump_until(
            lambda: backend.running_jobs().get(big) is not None
            and backend.running_jobs()[big].num_workers == 2
            and small in backend.running_jobs()), _logs(tmp_path, small)

        # small completes -> big scales back to 4; everything finishes.
        assert pump_until(
            lambda: store.get_job(small) is not None
            and store.get_job(small).status == JobStatus.COMPLETED)
        assert pump_until(
            lambda: store.get_job(big) is not None
            and store.get_job(big).status == JobStatus.COMPLETED,
            timeout=TIMEOUT), _logs(tmp_path, big)
        assert latest_step(ckpt_dir) == 30  # 6 epochs x 5 steps
        rows = read_epoch_csv(
            os.path.join(backend.metrics_dir, f"{big}.csv"))
        assert {int(r["workers"]) for r in rows} >= {2, 4}, rows
    finally:
        sched.stop()
        backend.close()
