"""The small-scope model checker: the real scheduler holds every
invariant over the bounded profile (≥ 2,000 states, the CI gate), the
seeded-bug variants are each CAUGHT with a deterministic replayable
counterexample, and exploration itself is deterministic."""

import json

import pytest

from vodascheduler_tpu.analysis import modelcheck
from vodascheduler_tpu.analysis.modelcheck import (
    JobShape,
    ModelConfig,
    bounded_config,
    deep_config,
    explore,
    replay_counterexample,
)
from vodascheduler_tpu.obs import audit as obs_audit


def small_config(**overrides) -> ModelConfig:
    base = dict(
        jobs=(JobShape("j0", min_chips=1, max_chips=4, epochs=1),
              JobShape("j1", min_chips=2, max_chips=4, epochs=1)),
        hosts=(("host-0", 4),),
        depth=6,
        max_states=300,
        faults=("start",),
        deletable=("j0",),
    )
    base.update(overrides)
    return ModelConfig(**base)


class TestBoundedProfile:
    def test_real_scheduler_holds_invariants_at_scale(self):
        """The acceptance criterion: the bounded profile passes on main
        AND explores non-trivially (≥ 2,000 unique states) so the bound
        cannot silently collapse."""
        result = explore(bounded_config())
        assert result.counterexample is None, json.dumps(
            result.counterexample, indent=1)
        assert result.states >= modelcheck.MIN_BOUNDED_STATES
        assert result.transitions > result.states
        assert result.leaves_drained > 0

    def test_exploration_is_deterministic(self):
        r1 = explore(small_config())
        r2 = explore(small_config())
        assert (r1.states, r1.transitions, r1.leaves_drained) == \
            (r2.states, r2.transitions, r2.leaves_drained)
        assert r1.counterexample is None and r2.counterexample is None


class TestSeededBugs:
    """The checker's teeth: deliberately broken scheduler variants must
    be caught, and their counterexamples must replay."""

    def test_keep_booking_on_revert_caught(self):
        result = explore(bounded_config(variant="keep-booking-on-revert"))
        ce = result.counterexample
        assert ce is not None
        assert ce["violation"].startswith("waiting_holds_chips")
        # The failing interleaving necessarily armed the start fault
        # whose revert path carries the seeded bug.
        assert any(a == "fault:start" for a in ce["path"])

    def test_eager_free_on_delete_caught(self):
        result = explore(bounded_config(variant="eager-free-on-delete"))
        ce = result.counterexample
        assert ce is not None
        assert ce["violation"].startswith("double_booked_host")
        assert any(a.startswith("delete:") for a in ce["path"])

    def test_counterexample_replays_deterministically(self):
        result = explore(bounded_config(variant="keep-booking-on-revert"))
        ce = result.counterexample
        first = replay_counterexample(ce)
        second = replay_counterexample(ce)
        assert first and first == second
        assert any(p.startswith("waiting_holds_chips") for p in first)

    def test_counterexample_survives_json_round_trip(self):
        """The record is a plain replayable artifact: through JSON and
        back, it still reproduces."""
        result = explore(bounded_config(variant="eager-free-on-delete"))
        rec = json.loads(json.dumps(result.counterexample))
        problems = replay_counterexample(rec)
        assert any(p.startswith("double_booked_host") for p in problems)

    def test_counterexample_satisfies_the_closed_schema(self):
        result = explore(bounded_config(variant="keep-booking-on-revert"))
        assert obs_audit.validate_record(result.counterexample) == []


class TestWorldMechanics:
    def test_config_round_trips(self):
        cfg = bounded_config()
        assert ModelConfig.from_dict(
            json.loads(json.dumps(cfg.to_dict()))) == cfg

    def test_fingerprint_ignores_absolute_time(self):
        w1 = modelcheck._World(small_config())
        w2 = modelcheck._World(small_config())
        w2.clock.advance(1e-7)  # below any timer; logical state equal
        assert w1.fingerprint() == w2.fingerprint()

    def test_fault_actions_disabled_until_first_submit(self):
        w = modelcheck._World(small_config())
        assert not any(a.startswith("fault:") for a in w.enabled())
        w.apply("submit:j0")
        assert any(a.startswith("fault:") for a in w.enabled())

    def test_drain_reaches_quiescence_on_clean_run(self):
        w = modelcheck._World(small_config())
        w.apply("submit:j0")
        assert w.drain() == []
        assert "j0" in w.backend.completed


class TestFaultInjection:
    """The fake backend's deterministic fault hooks (the chaos plane's
    unit of adversity, ROADMAP item 5)."""

    def test_one_shot_start_fault(self):
        from vodascheduler_tpu.cluster.fake import FakeClusterBackend
        from vodascheduler_tpu.common.clock import VirtualClock
        from vodascheduler_tpu.common.job import JobConfig, JobSpec

        backend = FakeClusterBackend(VirtualClock(start=0.0))
        backend.add_host("h", 4, announce=False)
        spec = JobSpec(name="x", config=JobConfig(min_num_chips=1,
                                                  max_num_chips=4,
                                                  epochs=1))
        backend.inject_fault("start")
        assert backend.armed_faults() == ["start"]
        with pytest.raises(RuntimeError, match="injected backend fault"):
            backend.start_job(spec, 2)
        assert backend.armed_faults() == []
        backend.start_job(spec, 2)  # one-shot: second attempt succeeds
        assert "x" in backend.running_jobs()

    def test_ack_fault_applies_then_raises(self):
        from vodascheduler_tpu.cluster.fake import FakeClusterBackend
        from vodascheduler_tpu.common.clock import VirtualClock
        from vodascheduler_tpu.common.job import JobConfig, JobSpec

        backend = FakeClusterBackend(VirtualClock(start=0.0))
        backend.add_host("h", 4, announce=False)
        spec = JobSpec(name="x", config=JobConfig(min_num_chips=1,
                                                  max_num_chips=4,
                                                  epochs=1))
        backend.start_job(spec, 2)
        backend.inject_fault("scale_ack")
        with pytest.raises(RuntimeError):
            backend.scale_job("x", 4)
        # The resize APPLIED before the ack crashed: backend truth
        # diverged from what the caller saw.
        assert backend.running_jobs()["x"].num_workers == 4

    def test_unknown_fault_kind_rejected(self):
        from vodascheduler_tpu.cluster.fake import FakeClusterBackend
        from vodascheduler_tpu.common.clock import VirtualClock

        backend = FakeClusterBackend(VirtualClock(start=0.0))
        with pytest.raises(ValueError):
            backend.inject_fault("gremlins")


def small_crash_config(**overrides):
    """A reduced durability world for the fast tier (the full crash
    profile runs in CI via `make modelcheck-crash` and in the slow
    tier below): 2 jobs, 1 quiescent + 1 torn crash point, fence on."""
    from vodascheduler_tpu.analysis.modelcheck import crash_config
    import dataclasses

    base = dataclasses.replace(
        crash_config(),
        jobs=(JobShape("j0", min_chips=1, max_chips=4, epochs=2),
              JobShape("j1", min_chips=2, max_chips=4, epochs=1)),
        depth=7, max_states=250, faults=("start",), churn_hosts=(),
        crash_points=(2,))
    return dataclasses.replace(base, **overrides)


class TestCrashProfile:
    """The durability plane's proof layer (doc/durability.md): crash at
    any action prefix + recover satisfies every invariant, and the
    three seeded journaling bugs are each caught with a replayable
    counterexample."""

    def test_small_crash_world_holds_invariants(self):
        result = explore(small_crash_config())
        assert result.counterexample is None, json.dumps(
            result.counterexample, indent=1)
        assert result.states > 50

    def test_crash_exploration_is_deterministic(self):
        r1 = explore(small_crash_config())
        r2 = explore(small_crash_config())
        assert (r1.states, r1.transitions) == (r2.states, r2.transitions)

    def test_crash_config_round_trips(self):
        from vodascheduler_tpu.analysis.modelcheck import crash_config
        cfg = crash_config()
        assert ModelConfig.from_dict(
            json.loads(json.dumps(cfg.to_dict()))) == cfg

    def test_unknown_variant_fails_loudly(self):
        with pytest.raises(ValueError, match="durability variant"):
            explore(small_crash_config(variant="keep-booking-on-revert"))

    @pytest.mark.parametrize("variant,invariant", [
        ("skip-journal-on-commit", "crash_recovery_divergence"),
        ("apply-before-append", "recovery_unjournaled_grant"),
        ("stale-epoch-accepted", "stale_epoch_write"),
    ])
    def test_durability_teeth_caught_and_replayable(self, variant,
                                                    invariant):
        from vodascheduler_tpu.analysis.modelcheck import crash_config
        result = explore(crash_config(variant=variant))
        assert result.counterexample is not None, \
            f"seeded durability bug {variant} was MISSED"
        assert result.counterexample["violation"].startswith(invariant), \
            result.counterexample["violation"]
        problems = replay_counterexample(json.loads(
            json.dumps(result.counterexample)))
        assert problems, "counterexample did not reproduce on replay"
        assert any(p.startswith(invariant) for p in problems)
        assert not obs_audit.validate_record(result.counterexample)

    def test_crash_invariants_documented_in_catalog(self):
        for inv in ("crash_recovery_divergence",
                    "recovery_unjournaled_grant", "stale_epoch_write"):
            assert inv in modelcheck.INVARIANTS


@pytest.mark.slow
class TestDeepProfile:
    def test_deep_profile_holds_invariants(self):
        result = explore(deep_config())
        assert result.counterexample is None, json.dumps(
            result.counterexample, indent=1)
        assert result.states >= 4 * modelcheck.MIN_BOUNDED_STATES

    def test_crash_profile_holds_invariants_at_scale(self):
        """The CI acceptance: crash-at-any-prefix + recover satisfies
        all invariants over >= 2,000 unique states."""
        from vodascheduler_tpu.analysis.modelcheck import crash_config
        result = explore(crash_config())
        assert result.counterexample is None, json.dumps(
            result.counterexample, indent=1)
        assert result.states >= modelcheck.MIN_BOUNDED_STATES
