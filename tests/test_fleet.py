"""Fleet control plane (doc/observability.md "Fleet decide"): the
concurrent multi-pool decide coordinator, the cross-pool admission
router, the native fleet batch kernels' differential proofs, the
16-pool teardown hygiene, and the perf_scale schema-5 fleet point."""

import json
import os
import random
import threading
import urllib.request

import pytest

from vodascheduler_tpu.allocator import ResourceAllocator
from vodascheduler_tpu.cluster.fake import FakeClusterBackend
from vodascheduler_tpu.common.clock import VirtualClock
from vodascheduler_tpu.common.events import EventBus, EventQueueFull, JobEvent
from vodascheduler_tpu.common.job import JobConfig, JobSpec
from vodascheduler_tpu.common.metrics import Registry
from vodascheduler_tpu.common.store import JobStore
from vodascheduler_tpu.common.types import EventVerb
from vodascheduler_tpu.obs import ROUTE_REASONS, audit as obs_audit
from vodascheduler_tpu.obs import tracer as obs_tracer
from vodascheduler_tpu.placement import PlacementManager
from vodascheduler_tpu.placement.topology import PoolTopology
from vodascheduler_tpu.scheduler import FleetCoordinator, FleetRouter, Scheduler
from vodascheduler_tpu.service import AdmissionService

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spec(name, pool="", min_chips=1, max_chips=2, collectives=None):
    return JobSpec(name=name, pool=pool,
                   config=JobConfig(min_num_chips=min_chips,
                                    max_num_chips=max_chips, epochs=1),
                   collectives=collectives)


def build_fleet(pools=("a", "b"), chips=(8, 8), topologies=None,
                router_enabled=True, rate_limit=0.5):
    clock = VirtualClock(start=1753760000.0)
    tracer = obs_tracer.Tracer(clock=clock, ring_size=512)
    store = JobStore()
    bus = EventBus()
    allocator = ResourceAllocator(store)
    schedulers = {}
    backends = {}
    for i, pool in enumerate(pools):
        backend = FakeClusterBackend(clock)
        topo = topologies[i] if topologies else None
        if topo is not None:
            for coord in topo.host_coords():
                backend.add_host(topo.host_name(coord),
                                 topo.chips_per_host, announce=False)
        else:
            backend.add_host(f"{pool}-host-0", chips[i], announce=False)
        pm = PlacementManager(pool, topology=topo)
        schedulers[pool] = Scheduler(
            pool, backend, store, allocator, clock, bus=bus,
            placement_manager=pm, algorithm="ElasticFIFO",
            rate_limit_seconds=rate_limit, tracer=tracer)
        backends[pool] = backend
    router = FleetRouter(schedulers, enabled=router_enabled,
                         tracer=tracer, bus=bus)
    fleet = FleetCoordinator(schedulers, workers=4, tracer=tracer,
                             router=router)
    admission = AdmissionService(store, bus, clock, valid_pools=set(pools),
                                 tracer=tracer, router=router)
    return (clock, store, bus, schedulers, backends, router, fleet,
            admission, tracer)


class TestFleetRouter:
    def test_explicit_pool_passes_through(self):
        _, _, _, scheds, _, router, _, _, tracer = build_fleet()
        pool, reasons = router.route(_spec("j", pool="a"))
        assert pool == "a"
        assert reasons == ["explicit_pool"]

    def test_unpooled_spec_routes_to_freest_pool(self):
        (clock, store, bus, scheds, _, router, _, admission,
         tracer) = build_fleet(chips=(8, 2))
        pool, reasons = router.route(_spec("j"))
        assert pool == "a"  # 8 free chips beats 2
        assert "best_score" in reasons

    def test_auto_is_routed_and_tie_breaks_deterministically(self):
        _, _, _, _, _, router, _, _, _ = build_fleet(chips=(4, 4))
        pool, _ = router.route(_spec("j", pool="auto"))
        assert pool == "a"  # equal scores: lexicographic pool name

    def test_affinity_steers_comms_heavy_family(self):
        # Equal capacity; pool b has the denser host block. A job with a
        # heavy collectives descriptor prefers b; a zero-comms job ties
        # to a.
        topo_a = PoolTopology(torus_dims=(8,), host_block=(1,))
        topo_b = PoolTopology(torus_dims=(4, 2), host_block=(2, 2))
        _, _, _, _, _, router, _, _, tracer = build_fleet(
            topologies=[topo_a, topo_b])
        heavy = _spec("llm", collectives={"allreduce_bytes_per_chip": 4e9,
                                          "comms_fraction": 0.3})
        pool, reasons = router.route(heavy)
        assert pool == "b"
        assert "affinity_preferred" in reasons
        pool, reasons = router.route(_spec("tiny"))
        assert pool == "a"
        assert "affinity_preferred" not in reasons

    def test_router_disabled_static_path(self):
        _, _, _, _, _, router, _, _, _ = build_fleet(router_enabled=False)
        with pytest.raises(ValueError):
            router.route(_spec("j", pool=""))
        # Explicit pools still pass through when disabled.
        pool, reasons = router.route(_spec("j", pool="b"))
        assert pool == "b" and reasons == ["explicit_pool"]

    def test_fleet_route_records_schema_valid(self):
        _, _, _, _, _, router, _, _, tracer = build_fleet()
        router.route(_spec("j1"))
        router.route(_spec("j2", pool="a"))
        recs = tracer.records(kind="fleet_route")
        assert len(recs) == 2
        for rec in recs:
            assert obs_audit.validate_record(rec) == []
            assert set(rec["reasons"]) <= ROUTE_REASONS
        stats = router.stats()
        assert stats["decisions_total"] == 2
        assert stats["by_reason"]["explicit_pool"] == 1

    def test_inflight_correction_spreads_a_burst(self):
        # A bulk batch routes every spec before its CREATEs publish, so
        # live backlog is frozen — the in-flight correction must spread
        # the burst instead of dumping it all on one argmax pool.
        (clock, store, bus, scheds, _, router, _, admission,
         tracer) = build_fleet(chips=(8, 8), rate_limit=1000.0)
        results = admission.create_training_jobs(
            [_spec(f"j{i}") for i in range(8)])
        assert all("error" not in r for r in results)
        routed = [store.get_job(r["name"]).pool for r in results]
        assert set(routed) == {"a", "b"}
        assert 2 <= routed.count("a") <= 6  # roughly balanced

    def test_failed_batch_aborts_routes_no_phantom_backlog(self):
        # A rejected burst must leave the in-flight correction and the
        # audit stream exactly as it found them: retried 429s/400s
        # would otherwise accrete phantom backlog that permanently
        # skews future scores, and the trace would assert placements
        # that never happened.
        (clock, store, bus, scheds, _, router, fleet, admission,
         tracer) = build_fleet(rate_limit=1000.0)
        bad = [_spec("ok1"), _spec("bad", pool="nope"), _spec("ok2")]
        results = admission.create_training_jobs(bad)
        assert any("error" in r for r in results)
        assert router._routed_to == {}
        assert tracer.records(kind="fleet_route") == []
        assert router.stats()["decisions_total"] == 0
        # A committed burst counts and audits normally afterwards.
        good = admission.create_training_jobs([_spec("ok3"), _spec("ok4")])
        assert all("error" not in r for r in good)
        assert len(tracer.records(kind="fleet_route")) == 2
        assert router.stats()["decisions_total"] == 2

    def test_load_cache_is_version_keyed(self):
        (clock, store, bus, scheds, _, router, fleet, admission,
         tracer) = build_fleet(rate_limit=1000.0)
        router.route(_spec("j1"))
        token1 = router._load_cache[0]
        router.route(_spec("j2"))
        assert router._load_cache[0] == token1  # quiet fleet: cache held
        # A scheduler mutation invalidates on the next route.
        admission.create_training_job(_spec("j3", pool="a"))
        clock.advance(2.0)
        router.route(_spec("j4"))
        assert router._load_cache[0] != token1

    def test_routed_admission_lands_and_completes(self):
        (clock, store, bus, scheds, backends, router, fleet, admission,
         tracer) = build_fleet()
        name = admission.create_training_job(_spec("solo"))
        job = store.get_job(name)
        assert job.pool in ("a", "b")
        clock.advance(5.0)
        assert name in scheds[job.pool].ready_jobs
        # The OTHER pool never heard of it.
        other = "b" if job.pool == "a" else "a"
        assert name not in scheds[other].ready_jobs


class TestFleetCoordinator:
    def test_run_fleet_pass_runs_every_pool_and_bumps_generation(self):
        (clock, store, bus, scheds, _, router, fleet, admission,
         tracer) = build_fleet(rate_limit=0.0)
        for i in range(4):
            admission.create_training_job(_spec(f"j{i}"))
        clock.advance(2.0)
        out = fleet.run_fleet_pass()
        assert out["generation"] == 1
        assert sorted(out["pools"]) == ["a", "b"]
        assert set(out["per_pool_ms"]) == {"a", "b"}
        out2 = fleet.run_fleet_pass()
        assert out2["generation"] == 2
        spans = [r for r in tracer.records(kind="span")
                 if r.get("name") == "fleet"]
        assert len(spans) == 2
        fleet.close()

    def test_fleet_snapshot_is_lock_free_and_correct(self):
        (clock, store, bus, scheds, _, router, fleet, admission,
         tracer) = build_fleet(rate_limit=0.0)
        admission.create_training_job(_spec("j0", pool="a"))
        clock.advance(2.0)
        # Snapshot must not block even while a scheduler lock is held.
        with scheds["a"]._lock:
            snap = fleet.fleet_snapshot()
        assert snap["totals"]["pools"] == 2
        assert snap["pools"]["a"]["ready_jobs"] == 1
        assert snap["pools"]["a"]["total_chips"] == 8
        assert snap["pools"]["b"]["ready_jobs"] == 0

    def test_fleet_stats_shape(self):
        (clock, store, bus, scheds, _, router, fleet, admission,
         tracer) = build_fleet(rate_limit=0.0)
        admission.create_training_job(_spec("j0"))
        clock.advance(2.0)
        fleet.run_fleet_pass()
        stats = fleet.fleet_stats()
        assert set(stats["profile"]) == {"a", "b"}
        for pool_stats in stats["profile"].values():
            assert "decide_ms_p95" in pool_stats
        assert stats["router"]["decisions_total"] >= 1
        assert stats["last_pass"]["generation"] == fleet.generation
        fleet.close()

    def test_pool_failure_is_isolated(self):
        (clock, store, bus, scheds, _, router, fleet, admission,
         tracer) = build_fleet(rate_limit=0.0)

        def boom():
            raise RuntimeError("pool a broke")

        scheds["a"].pump = boom
        out = fleet.run_fleet_pass()  # must not raise
        assert "b" in out["per_pool_ms"]
        fleet.close()

    def test_close_is_idempotent_and_joins_threads(self):
        before = {t.ident for t in threading.enumerate()}
        (clock, store, bus, scheds, _, router, fleet, admission,
         tracer) = build_fleet(rate_limit=0.0)
        fleet.run_fleet_pass()
        fleet.close()
        fleet.close()
        # No fleet thread born in this test survives the close (other
        # tests' unclosed fleets may still park idle daemon workers).
        leaked = [t.name for t in threading.enumerate()
                  if t.name.startswith("voda-fleet")
                  and t.ident not in before]
        assert leaked == []
        with pytest.raises(RuntimeError):
            fleet._pool_executor()


class TestTeardownHygiene:
    """Satellite: pools >> 8 must tear down cleanly — drainer threads
    enumerable and joined, no metric identity collisions, no leaked
    voda-* threads."""

    def test_16_pool_storm_and_clean_teardown(self):
        before = {t.ident for t in threading.enumerate()}
        pools = tuple(f"p{i:02d}" for i in range(16))
        (clock, store, bus, scheds, backends, router, fleet, admission,
         tracer) = build_fleet(pools=pools, chips=(4,) * 16,
                               rate_limit=0.0)
        specs = [_spec(f"j{i}") for i in range(64)]
        results = admission.create_training_jobs(specs)
        assert all("error" not in r for r in results)
        clock.advance(5.0)
        fleet.run_fleet_pass()
        # Drainer threads are enumerable by name while live.
        for t in bus.drainer_threads():
            assert t.name.startswith("voda-event-drain-")
        fleet.close()
        bus.close()
        for sched in scheds.values():
            sched.stop()
        # Everything joined: no fleet or drainer threads survive.
        leaked = [t.name for t in threading.enumerate()
                  if t.name.startswith(("voda-fleet", "voda-event-drain"))
                  and t.ident not in before]
        assert leaked == []
        assert bus.drainer_threads() == []

    def test_bus_close_refuses_new_handoffs(self):
        bus = EventBus()
        bus.close()
        with pytest.raises(EventQueueFull):
            bus.publish_many("a", (JobEvent(EventVerb.CREATE, "j"),),
                             all_or_nothing=True)
        with pytest.raises(EventQueueFull):
            bus.publish_many_multi({"a": [JobEvent(EventVerb.CREATE, "j")]})
        # Best-effort publish after close drops (logged), never raises.
        bus.publish("a", JobEvent(EventVerb.CREATE, "j"))
        assert bus.pending("a") == 0

    def test_registry_rejects_identity_collision(self):
        registry = Registry()
        registry.counter("voda_x_total", "x", const_labels={"pool": "a"})
        registry.counter("voda_x_total", "x", const_labels={"pool": "b"})
        with pytest.raises(ValueError):
            registry.counter("voda_x_total", "x",
                             const_labels={"pool": "a"})


class TestDebugFleetRoute:
    def test_debug_fleet_and_cli_rendering(self):
        from vodascheduler_tpu.service.rest import make_scheduler_server
        (clock, store, bus, scheds, _, router, fleet, admission,
         tracer) = build_fleet(rate_limit=0.0)
        admission.create_training_job(_spec("j0"))
        clock.advance(2.0)
        fleet.run_fleet_pass()
        server = make_scheduler_server(scheds, Registry(),
                                       host="127.0.0.1", port=0,
                                       fleet=fleet)
        server.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/debug/fleet",
                    timeout=10.0) as resp:
                stats = json.loads(resp.read().decode())
        finally:
            server.stop()
        assert stats["totals"]["pools"] == 2
        assert "router" in stats and "profile" in stats
        # CLI rendering smoke: must not raise on the live payload.
        from vodascheduler_tpu.cli import _print_fleet
        _print_fleet(stats)


class TestNativeFleetKernels:
    """Differential proofs for the new batch kernels: native ==
    python fastpath == oracle, including tie evolution and dict order
    (fastpath.self_check runs native-forced; the explicit layer tests
    here pin native == python with the floors zeroed)."""

    def test_self_check_native_and_pure(self, monkeypatch):
        from vodascheduler_tpu import native
        from vodascheduler_tpu.algorithms import fastpath
        if native.get_lib() is None:
            pytest.skip("native kernels unavailable")
        assert fastpath.self_check(n_pools=60) == []
        monkeypatch.setenv("VODA_NO_NATIVE", "1")
        assert fastpath.self_check(n_pools=30) == []

    def test_native_equals_python_fastpath_all_algorithms(self,
                                                          monkeypatch):
        import copy

        from vodascheduler_tpu import native
        from vodascheduler_tpu.algorithms import fastpath
        from vodascheduler_tpu.algorithms.base import InvalidAllocationError
        if native.get_lib() is None:
            pytest.skip("native kernels unavailable")
        monkeypatch.setattr(fastpath, "_SWEEP_NATIVE_MIN", 0)
        monkeypatch.setattr(fastpath, "_ET_PHASES_NATIVE_MIN", 0)
        rng = random.Random(42)
        kernels = (fastpath.fifo, fastpath.elastic_fifo, fastpath.srjf,
                   fastpath.elastic_srjf, fastpath.tiresias,
                   fastpath.elastic_tiresias)
        for trial in range(60):
            jobs, total = fastpath.random_pool(rng,
                                               degenerate=(trial % 5 == 2))
            for fn in kernels:
                def run(no_native):
                    if no_native:
                        os.environ["VODA_NO_NATIVE"] = "1"
                    else:
                        os.environ.pop("VODA_NO_NATIVE", None)
                    try:
                        try:
                            return fn(copy.deepcopy(jobs), total)
                        except InvalidAllocationError as e:
                            return ("raises", str(e))
                    finally:
                        os.environ.pop("VODA_NO_NATIVE", None)
                a, b = run(False), run(True)
                assert a == b, (trial, fn.__name__)
                if isinstance(a, dict):
                    assert list(a) == list(b), (trial, fn.__name__,
                                                "dict order diverged")

    def test_comms_score_native_equals_reference(self):
        from vodascheduler_tpu import native
        if native.get_lib() is None:
            pytest.skip("native kernels unavailable")
        rng = random.Random(11)
        for trial in range(30):
            topo = PoolTopology.parse(
                rng.choice(["4x4x4/2x2x1", "8x8/2x2", "16/1", "4x4/1x1"]))
            pm = PlacementManager("p", topology=topo)
            for coord in topo.host_coords():
                pm.add_host(topo.host_name(coord), topo.chips_per_host)
            for _ in range(rng.randint(1, 12)):
                pm.place({f"j{k}": rng.randint(1, 6)
                          for k in range(rng.randint(1, 10))})
            pm.set_comms_weights({f"j{k}": rng.randint(0, 8)
                                  for k in range(10)})
            ref = pm._fleet_stats_reference()
            nat = pm._fleet_stats_native()
            assert nat is not None
            assert tuple(ref) == tuple(nat), trial

    def test_no_native_fallbacks_return_none(self, monkeypatch):
        from vodascheduler_tpu import native
        monkeypatch.setenv("VODA_NO_NATIVE", "1")
        assert native.alloc_sweep([0], [1], [1], [1], 1, 0) is None
        assert native.et_schedule([0], [1], [1], [1], [0], [0], [0], 1,
                                  10, 2.0, [0], [0, 3],
                                  [0.0, 1.0, 2.0]) is None
        assert native.comms_score([2], [0, 1], [0], [1], [0]) is None


class TestFleetModelcheck:
    """Satellite: the 2-pool fleet profile and its seeded-bug teeth."""

    def test_fleet_profile_clean(self):
        from vodascheduler_tpu.analysis import modelcheck as mc
        config = mc.fleet_config()
        # Bounded for tier-1 runtime; the full profile runs via
        # `modelcheck --profile fleet`.
        import dataclasses
        config = dataclasses.replace(config, depth=8, max_states=600)
        result = mc.explore(config)
        assert result.ok, result.counterexample
        assert result.states >= 200

    def test_misrouting_admission_caught_and_replays(self):
        from vodascheduler_tpu.analysis import modelcheck as mc
        result = mc.explore(
            mc.fleet_config(variant="route-book-start-mismatch"))
        assert result.counterexample is not None
        assert result.counterexample["violation"].startswith(
            "cross_pool_booking")
        assert mc.replay_counterexample(result.counterexample)

    def test_fleet_invariants_documented(self):
        from vodascheduler_tpu.analysis.modelcheck import INVARIANTS
        assert "cross_pool_booking" in INVARIANTS
        assert "stranded_between_pools" in INVARIANTS


class TestFleetPerfPoint:
    """Schema-5 fleet point: shape, gate bounds, and the committed
    baseline's 100k acceptance pins."""

    def _mini_fleet_point(self):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "perf_scale", os.path.join(REPO, "scripts", "perf_scale.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod, mod.run_fleet_point(800, n_pools=8, passes=2, seed=3)

    def test_fleet_point_shape(self):
        mod, point = self._mini_fleet_point()
        assert point["pools"] == 8
        assert len(point["per_pool"]) == 8
        assert point["per_pool_decide_ms"]["p95"] >= 0
        assert point["fleet_pass_wall_ms"]["mean"] > 0
        assert point["router"]["decisions_total"] >= 800
        assert point["router"]["route_ms"]["p99"] >= 0
        algos = {p["algorithm"] for p in point["per_pool"].values()}
        assert len(algos) >= 2  # heterogeneous

    def test_fleet_gate_bounds_and_absolute_pin(self, capsys):
        mod, point = self._mini_fleet_point()
        baseline = {"schema": mod.SCHEMA, "curves": [], "ingestion": [],
                    "fleet": [point]}
        fresh = {"schema": mod.SCHEMA, "curves": [], "ingestion": [],
                 "fleet": [json.loads(json.dumps(point))]}
        assert mod.compare(baseline, fresh) == []
        # A doctored per-pool decide p95 past the absolute 50 ms pin
        # fails even within the relative tolerance band — the pin binds
        # the >=100k headline point.
        head = json.loads(json.dumps(point))
        head["total_jobs"] = 100000
        doctored = json.loads(json.dumps(head))
        doctored["per_pool_decide_ms"]["p95"] = max(
            55.0, point["per_pool_decide_ms"]["p95"])
        problems = mod.compare(
            {"schema": mod.SCHEMA, "fleet": [head]},
            {"schema": mod.SCHEMA, "fleet": [doctored]},
            tolerance=1000.0)
        assert any("50 ms fleet pin" in p for p in problems)
        # A missing baseline fleet point is loud, not silent.
        problems = mod.compare({"schema": mod.SCHEMA},
                               {"schema": mod.SCHEMA, "fleet": [point]})
        assert any("no baseline fleet point" in p for p in problems)
        capsys.readouterr()

    def test_committed_baseline_fleet_acceptance(self):
        """The acceptance pins, against the committed artifact: 100k
        jobs across >= 8 heterogeneous pools, per-pool decide p95 under
        50 ms, fleet throughput and router p99 present."""
        with open(os.path.join(REPO, "doc", "perf_baseline.json")) as f:
            baseline = json.load(f)
        assert baseline["schema"] >= 5
        fleet = {c["total_jobs"]: c for c in baseline["fleet"]}
        assert 100000 in fleet, "100k fleet point missing from baseline"
        head = fleet[100000]
        assert head["pools"] >= 8
        algos = {p["algorithm"] for p in head["per_pool"].values()}
        assert len(algos) >= 2
        assert 0 < head["per_pool_decide_ms"]["p95"] < 50.0
        assert head["fleet_pass_speedup"] > 1.5
        assert head["fleet_throughput_jobs_per_s"] > 0
        assert head["router"]["route_ms"]["p99"] > 0
        # The gate-bounded small fleet point rides alongside.
        assert any(n < 100000 for n in fleet)
