"""Hardware-bench plumbing, hermetically (VODA_HWBENCH_ON_CPU tiny
shapes): the measurement path the driver runs on the real chip must
produce a complete, well-formed section even off-accelerator."""

import os

import pytest

from tests import helpers

# Model points step real models (reshard path -> get_abstract_mesh) and
# attention points build the Pallas flash kernel (CompilerParams): both
# newer-jax surfaces must exist for the measured rows to materialize —
# on older jax the points error out and the asserted keys never appear.
needs_new_jax = pytest.mark.skipif(
    not (helpers.JAX_HAS_ABSTRACT_MESH
         and helpers.JAX_HAS_PALLAS_COMPILER_PARAMS),
    reason=f"{helpers.NEEDS_ABSTRACT_MESH}; {helpers.NEEDS_PALLAS_COMPILER_PARAMS}")


@pytest.fixture(autouse=True)
def cpu_escape_hatch(monkeypatch):
    monkeypatch.setenv("VODA_HWBENCH_ON_CPU", "1")


@needs_new_jax
def test_model_point_and_attention_point():
    from vodascheduler_tpu.runtime.hwbench import run_hardware_bench
    out = run_hardware_bench(model_points=(("llama_tiny", 4),),
                             attention_points=((2, 128),), moe_batch=None)
    assert out["models"] and out["attention"]
    model = out["models"][0]
    assert model["model"] == "llama_tiny"
    assert model["step_time_ms"] > 0
    assert model["tokens_per_sec"] > 0
    assert model["num_params"] > 0
    attn = out["attention"][0]
    assert attn["flash_ms"] > 0 and attn["xla_ms"] > 0
    assert "flash_speedup" in attn


def test_point_errors_are_isolated():
    from vodascheduler_tpu.runtime.hwbench import run_hardware_bench
    out = run_hardware_bench(model_points=(("no_such_model", 4),),
                             attention_points=(), moe_batch=None)
    assert "error" in out["models"][0]


@needs_new_jax
def test_moe_dispatch_compare_hermetic():
    """The gather/routed/dense comparison runs hermetically on a tiny
    config and reports active-param MFU for the gather flagship."""
    from vodascheduler_tpu.models import mixtral
    from vodascheduler_tpu.runtime.hwbench import bench_moe_dispatch

    out = bench_moe_dispatch(2, model_name="mixtral_tiny",
                             base_cfg=mixtral.MIXTRAL_TINY)
    assert out["gather"]["step_time_ms"] > 0
    assert out["routed_step_ms"] > 0
    assert out["dense_step_ms"] > 0
    assert out["gather_speedup_vs_dense"] > 0
    # MoE convention: active < total params (top_k=2 of 4 experts).
    assert 0 < out["gather"]["num_params_active"] < out["gather"]["num_params"]
    # The af tuning row (adafactor + dots_attn on gather dispatch).
    assert out["gather_af"]["step_time_ms"] > 0, out["gather_af"]


def test_refuses_cpu_without_escape_hatch(monkeypatch):
    monkeypatch.delenv("VODA_HWBENCH_ON_CPU")
    from vodascheduler_tpu.runtime.hwbench import run_hardware_bench
    with pytest.raises(RuntimeError, match="accelerator"):
        run_hardware_bench()


def test_two_point_differencing_cancels_overhead():
    """The two-point estimator must subtract fixed per-call overhead:
    feed it a fake timer where t(k) = C + k*s and check it returns s."""
    from vodascheduler_tpu.runtime import hwbench

    class FakeClock:
        def __init__(self):
            self.t = 0.0

        def __call__(self):
            return self.t

    clock = FakeClock()

    def make_scanned(k):
        def run():
            clock.t += 5.0 + 0.25 * k  # 5s overhead + 0.25s/iter
            return 0.0
        return run

    real_counter = hwbench.time.perf_counter
    real_fetch = hwbench._fetch
    hwbench.time.perf_counter = clock
    hwbench._fetch = lambda x: 0.0
    try:
        s = hwbench.time_per_iteration(make_scanned, k_small=2, k_big=10,
                                       reps=1)
    finally:
        hwbench.time.perf_counter = real_counter
        hwbench._fetch = real_fetch
    assert abs(s - 0.25) < 1e-9


def test_every_bench_point_has_flops_structure():
    """Config-rot guard: every model bench.py ships to the chip must
    have an analytic-FLOPs structure entry — in r5 a missing llama_1b
    entry burned the chip slot and surfaced as an unrelated XLA OOM
    from the retry path."""
    from vodascheduler_tpu.runtime.hwbench import _lm_structure

    bench = _bench_module()
    for model_name, _ in bench.HW_MODEL_POINTS:
        n_layers, d_model = _lm_structure(model_name)
        assert n_layers > 0 and d_model > 0, model_name


@pytest.mark.slow
def test_stream_main_emits_parseable_lines():
    """hwbench --stream (the subprocess mode bench.py drives) emits one
    JSON line per completed item; bench.parse_hw_stream rebuilds the
    section dict from them — including from a truncated tail."""
    import json
    import subprocess
    import sys

    env = dict(os.environ, VODA_HWBENCH_ON_CPU="1", JAX_PLATFORMS="cpu")
    kwargs = json.dumps({"model_points": [["llama_tiny", 2]],
                         "attention_points": [[1, 64]],
                         "moe_batch": None})
    res = subprocess.run(
        [sys.executable, "-m", "vodascheduler_tpu.runtime.hwbench",
         "--stream", kwargs],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert res.returncode == 0, res.stderr[-500:]

    parse_hw_stream = _bench_module().parse_hw_stream
    out = parse_hw_stream(res.stdout)
    assert out["models"][0]["model"] == "llama_tiny"
    assert out["attention"][0]["flash_ms"] > 0
    assert "peak_bf16_tflops_per_chip" in out

    # Kill-mid-write salvage: drop the last line's tail — earlier points
    # must survive.
    truncated = res.stdout[: res.stdout.rfind("{")]
    partial = parse_hw_stream(truncated)
    assert partial["models"][0]["model"] == "llama_tiny"


def _bench_module():
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    return bench


def _debug_points(monkeypatch, bench, tmp_path, points):
    """Route maybe_hardware through the benchrunner with an injected
    point registry and tmp-path persistence (cache/journal/last-good),
    with the accelerator probe stubbed out."""
    import json
    monkeypatch.setenv("VODA_HWBENCH_ON_CPU", "1")
    monkeypatch.setenv("VODA_BENCH_POINTS_JSON", json.dumps(points))
    monkeypatch.setattr(bench, "_probe_backend",
                        lambda repo_dir: ("cpu", None))
    # Absolute paths win the os.path.join(repo_dir, ...) inside
    # maybe_hardware, so persistence lands in tmp_path while the workers
    # keep the real repo as cwd (they import the real package).
    monkeypatch.setattr(bench, "BENCHRUNNER_CACHE",
                        os.fspath(tmp_path / "cache.json"))
    monkeypatch.setattr(bench, "BENCHRUNNER_JOURNAL",
                        os.fspath(tmp_path / "journal.jsonl"))
    monkeypatch.setattr(bench, "LAST_GOOD_CACHE",
                        os.fspath(tmp_path / "doc" / "last_good.json"))


def test_wedged_point_is_skipped_and_stream_continues(tmp_path, monkeypatch):
    """The acceptance scenario end-to-end through bench.maybe_hardware:
    a wedged point (hang in its own subprocess — on the real chip a
    compile blocked in native code no signal can interrupt) is killed by
    the per-point watchdog, every OTHER point still measures, and the
    emitted section tags every registered row with no whole-stream stall
    error — the failure mode that cost r5 its _af/llama_1b/attention/
    MoE/resize rows."""
    bench = _bench_module()
    _debug_points(monkeypatch, bench, tmp_path, [
        {"point_id": "meta", "kind": "debug", "section": "meta", "risk": -1,
         "spec": {"behavior": "ok", "data": {"backend": "fake"}}},
        {"point_id": "model:m1:b8", "kind": "debug", "section": "model",
         "spec": {"behavior": "ok",
                  "data": {"model": "m1", "step_time_ms": 1.0}}},
        {"point_id": "model:wedge:b16", "kind": "debug", "section": "model",
         "risk": 5, "timeout_seconds": 2,
         "spec": {"behavior": "hang", "seconds": 600}},
        {"point_id": "resize:m1:b8", "kind": "debug", "section": "resize",
         "risk": 9,
         "spec": {"behavior": "ok",
                  "data": {"model": "m1", "resize_cost_seconds": 4.0}}},
    ])
    out = bench.maybe_hardware()
    assert out is not None and "error" not in out, out
    assert out["backend"] == "fake"
    by_model = {m["model"]: m for m in out["models"] if "model" in m}
    assert by_model["m1"]["provenance"] == "measured"
    wedge = [m for m in out["models"] if m.get("point_id")
             == "model:wedge:b16"][0]
    assert wedge["provenance"].startswith("skipped:watchdog_timeout")
    # The wedge did NOT take the later (riskier) resize point with it.
    assert out["resize"][0]["provenance"] == "measured"
    assert out["benchrunner"]["stats"] == {"total": 4, "measured": 3,
                                           "cached": 0, "skipped": 1}


def test_budget_exhaustion_tags_tail_and_keeps_head(tmp_path, monkeypatch):
    """The overall VODA_BENCH_HW_TIMEOUT budget: when a slow point eats
    it, the riskier tail points are tagged budget_exhausted (or killed by
    the clamped watchdog) — completed points are kept, nothing is
    silently absent."""
    bench = _bench_module()
    _debug_points(monkeypatch, bench, tmp_path, [
        {"point_id": "model:fast:b8", "kind": "debug", "section": "model",
         "spec": {"behavior": "ok", "data": {"model": "fast",
                                             "step_time_ms": 1.0}}},
        {"point_id": "model:hog:b8", "kind": "debug", "section": "model",
         "risk": 5, "spec": {"behavior": "hang", "seconds": 600}},
        {"point_id": "model:tail:b8", "kind": "debug", "section": "model",
         "risk": 9, "spec": {"behavior": "ok", "data": {"model": "tail"}}},
    ])
    # 6s total: the hog's own 60s debug timeout is clamped to the
    # remaining budget, so it dies at ~5.5s and the tail point finds
    # less than the 5s spawn floor left.
    monkeypatch.setenv("VODA_BENCH_HW_TIMEOUT", "6")
    out = bench.maybe_hardware()
    assert out is not None and "error" not in out, out
    rows = {m.get("model") or m.get("point_id"): m for m in out["models"]}
    assert rows["fast"]["provenance"] == "measured"
    assert rows["model:hog:b8"]["provenance"].startswith(
        "skipped:watchdog_timeout")
    assert rows["model:tail:b8"]["provenance"].startswith(
        "skipped:budget_exhausted")


def _redirect_repo_dir(monkeypatch, bench, tmp_path):
    """Make maybe_hardware see tmp_path as the repo root."""
    monkeypatch.setattr(bench.os.path, "dirname",
                        lambda p, _real=os.path.dirname: str(tmp_path)
                        if p == os.path.abspath(bench.__file__)
                        else _real(p))


def test_dead_tunnel_falls_back_to_cached_results(tmp_path, monkeypatch):
    """When the accelerator probe never succeeds (dead tunnel — the r3
    failure mode), maybe_hardware must emit the last-good cached results
    tagged cached_from, not a bare error marker."""
    import json

    bench = _bench_module()
    cached = {"backend": "tpu", "device_kind": "TPU v5 lite",
              "models": [{"model": "llama_350m", "mfu": 0.38}],
              "attention": []}
    (tmp_path / "doc").mkdir()
    (tmp_path / "doc" / "benchmarks_last_good.json").write_text(json.dumps(
        {"captured_at": "2026-07-30T05:30:00Z", "hardware": cached}))
    monkeypatch.setattr(bench, "_probe_backend",
                        lambda repo_dir: (None, "accelerator probe timed "
                                                "out (90s x3)"))
    _redirect_repo_dir(monkeypatch, bench, tmp_path)
    out = bench.maybe_hardware()
    assert out["models"] == cached["models"]
    assert out["cached_from"] == "2026-07-30T05:30:00Z"
    assert "timed out" in out["live_error"]


def test_dead_tunnel_without_cache_reports_error(tmp_path, monkeypatch):
    bench = _bench_module()
    monkeypatch.setattr(bench, "_probe_backend",
                        lambda repo_dir: (None, "probe died"))
    _redirect_repo_dir(monkeypatch, bench, tmp_path)
    out = bench.maybe_hardware()
    assert out == {"error": "probe died"}


def test_probe_retries_then_succeeds(monkeypatch, tmp_path):
    """_probe_backend must retry past transient flakes with backoff."""
    import subprocess
    import time
    import types

    bench = _bench_module()
    calls = {"n": 0}
    sleeps = []

    def fake_run(*a, **kw):
        calls["n"] += 1
        if calls["n"] < 3:
            raise subprocess.TimeoutExpired(cmd=a[0], timeout=kw["timeout"])
        return types.SimpleNamespace(returncode=0, stdout="cpu\n", stderr="")

    monkeypatch.setattr(subprocess, "run", fake_run)
    monkeypatch.setattr(time, "sleep", sleeps.append)
    backend, err = bench._probe_backend(str(tmp_path))
    assert backend == "cpu" and err is None
    assert calls["n"] == 3
    assert sleeps == [15, 30]  # backoff between attempts


def test_successful_run_writes_last_good_cache(tmp_path, monkeypatch):
    """A clean hardware run must refresh the last-good cache so the NEXT
    dead-tunnel round has something to fall back on — measured rows only
    (a skipped row is not evidence)."""
    import json

    bench = _bench_module()
    _debug_points(monkeypatch, bench, tmp_path, [
        {"point_id": "model:m1:b8", "kind": "debug", "section": "model",
         "spec": {"behavior": "ok",
                  "data": {"model": "m1", "step_time_ms": 1.0}}},
        {"point_id": "model:bad:b8", "kind": "debug", "section": "model",
         "risk": 5, "spec": {"behavior": "fail", "message": "boom"}},
    ])
    out = bench.maybe_hardware()
    assert "error" not in out, out
    cache = json.loads((tmp_path / "doc" / "last_good.json").read_text())
    assert cache["hardware"]["models"] == [{"model": "m1",
                                            "step_time_ms": 1.0,
                                            "provenance": "measured"}]
    assert cache["captured_at"]


def test_cached_backfill_rows_do_not_refresh_last_good(tmp_path, monkeypatch):
    """A row back-filled from the benchrunner cache (cached_from tag)
    must NOT be re-cached as fresh last-good evidence — its timestamp
    would renew forever."""
    from vodascheduler_tpu.benchrunner import point_from_dict
    from vodascheduler_tpu.benchrunner.cache import ResultCache

    bench = _bench_module()
    flaky = {"point_id": "model:flaky:b8", "kind": "debug",
             "section": "model", "risk": 5,
             "spec": {"behavior": "fail", "message": "transient"}}
    _debug_points(monkeypatch, bench, tmp_path, [
        {"point_id": "model:m1:b8", "kind": "debug", "section": "model",
         "spec": {"behavior": "ok",
                  "data": {"model": "m1", "step_time_ms": 1.0}}},
        flaky,
    ])
    seed = ResultCache(os.fspath(tmp_path / "cache.json"))
    seed.put("model:flaky:b8", point_from_dict(flaky).config_hash(),
             {"model": "flaky", "step_time_ms": 9.0})
    out = bench.maybe_hardware()
    by_model = {m.get("model"): m for m in out["models"]}
    assert by_model["flaky"]["provenance"].startswith("cached_from:")
    import json
    cache = json.loads((tmp_path / "doc" / "last_good.json").read_text())
    assert [m["model"] for m in cache["hardware"]["models"]] == ["m1"]


def test_cache_write_drops_error_rows_and_keeps_prior_on_empty(tmp_path):
    """Per-row failures in ANY section (models/attention/moe/resize) must
    not become fallback evidence, and a run where every model point
    errored must not clobber a previously good cache with models: []."""
    import json

    bench = _bench_module()
    good = {"models": [{"model": "m1", "mfu": 0.4},
                       {"model": "m2", "error": "OOM"}],
            "attention": [{"batch": 8, "seq": 1024, "flash_ms": 1.0},
                          {"batch": 1, "seq": 8192, "error": "boom"}],
            "moe": {"error": "RESOURCE_EXHAUSTED: " + "x" * 50},
            "resize": [{"model": "m1", "resize_cost_seconds": 9.0},
                       {"model": "m2", "error": "died"}]}
    bench.write_last_good(str(tmp_path), good)
    cache = json.loads(
        (tmp_path / "doc" / "benchmarks_last_good.json").read_text())
    hw = cache["hardware"]
    assert hw["models"] == [{"model": "m1", "mfu": 0.4}]
    assert hw["attention"] == [{"batch": 8, "seq": 1024, "flash_ms": 1.0}]
    assert "moe" not in hw
    assert hw["resize"] == [{"model": "m1", "resize_cost_seconds": 9.0}]

    # Per-variant failure INSIDE the moe dict (e.g. gather_af) is
    # stripped while the measured variants stay.
    mixed_moe = {"models": [{"model": "m1", "mfu": 0.4}],
                 "moe": {"gather": {"step_time_ms": 1.0},
                         "dense_step_ms": 2.0,
                         "gather_af": {"error": "OOM"}}}
    bench.write_last_good(str(tmp_path), mixed_moe)
    cache_moe = json.loads(
        (tmp_path / "doc" / "benchmarks_last_good.json").read_text())
    assert cache_moe["hardware"]["moe"] == {"gather": {"step_time_ms": 1.0},
                                            "dense_step_ms": 2.0}

    # Every moe variant errored per-variant: the section is dropped, not
    # cached as an empty dict masquerading as a successful capture.
    all_moe_bad = {"models": [{"model": "m1", "mfu": 0.4}],
                   "moe": {"gather": {"error": "OOM"},
                           "gather_af": {"error": "OOM"}}}
    bench.write_last_good(str(tmp_path), all_moe_bad)
    cache_bad = json.loads(
        (tmp_path / "doc" / "benchmarks_last_good.json").read_text())
    assert "moe" not in cache_bad["hardware"]

    all_bad = {"models": [{"model": "m1", "error": "regression"}],
               "attention": [{"batch": 8, "seq": 1024, "flash_ms": 2.0}]}
    bench.write_last_good(str(tmp_path), all_bad)
    cache2 = json.loads(
        (tmp_path / "doc" / "benchmarks_last_good.json").read_text())
    assert cache2["hardware"]["models"] == [{"model": "m1", "mfu": 0.4}]

    # Provenance-tagged rows: cached_from/skipped moe + resize rows must
    # not become last-good evidence either (a cached row carries its
    # live failure under live_error, not error — the key filter alone
    # would let it renew its timestamp forever).
    tagged = {"models": [{"model": "m1", "mfu": 0.4,
                          "provenance": "measured"}],
              "moe": {"gather": {"step_time_ms": 1.0},
                      "provenance": "cached_from:2026-07-30T05:30:00Z",
                      "live_error": "watchdog"},
              "resize": [{"model": "m1", "resize_cost_seconds": 9.0,
                          "provenance": "measured"},
                         {"model": "m2", "resize_cost_seconds": 8.0,
                          "provenance": "cached_from:2026-07-30T05:30:00Z",
                          "live_error": "watchdog"},
                         {"model": "m3",
                          "provenance": "skipped:budget_exhausted"}]}
    bench.write_last_good(str(tmp_path), tagged)
    cache3 = json.loads(
        (tmp_path / "doc" / "benchmarks_last_good.json").read_text())
    assert "moe" not in cache3["hardware"]
    assert [r["model"] for r in cache3["hardware"]["resize"]] == ["m1"]
