"""Hardware-bench plumbing, hermetically (VODA_HWBENCH_ON_CPU tiny
shapes): the measurement path the driver runs on the real chip must
produce a complete, well-formed section even off-accelerator."""

import os

import pytest


@pytest.fixture(autouse=True)
def cpu_escape_hatch(monkeypatch):
    monkeypatch.setenv("VODA_HWBENCH_ON_CPU", "1")


def test_model_point_and_attention_point():
    from vodascheduler_tpu.runtime.hwbench import run_hardware_bench
    out = run_hardware_bench(model_points=(("llama_tiny", 4),),
                             attention_points=((2, 128),), moe_batch=None)
    assert out["models"] and out["attention"]
    model = out["models"][0]
    assert model["model"] == "llama_tiny"
    assert model["step_time_ms"] > 0
    assert model["tokens_per_sec"] > 0
    assert model["num_params"] > 0
    attn = out["attention"][0]
    assert attn["flash_ms"] > 0 and attn["xla_ms"] > 0
    assert "flash_speedup" in attn


def test_point_errors_are_isolated():
    from vodascheduler_tpu.runtime.hwbench import run_hardware_bench
    out = run_hardware_bench(model_points=(("no_such_model", 4),),
                             attention_points=(), moe_batch=None)
    assert "error" in out["models"][0]


def test_moe_dispatch_compare_hermetic():
    """The gather/routed/dense comparison runs hermetically on a tiny
    config and reports active-param MFU for the gather flagship."""
    from vodascheduler_tpu.models import mixtral
    from vodascheduler_tpu.runtime.hwbench import bench_moe_dispatch

    out = bench_moe_dispatch(2, model_name="mixtral_tiny",
                             base_cfg=mixtral.MIXTRAL_TINY)
    assert out["gather"]["step_time_ms"] > 0
    assert out["routed_step_ms"] > 0
    assert out["dense_step_ms"] > 0
    assert out["gather_speedup_vs_dense"] > 0
    # MoE convention: active < total params (top_k=2 of 4 experts).
    assert 0 < out["gather"]["num_params_active"] < out["gather"]["num_params"]
    # The af tuning row (adafactor + dots_attn on gather dispatch).
    assert out["gather_af"]["step_time_ms"] > 0, out["gather_af"]


def test_refuses_cpu_without_escape_hatch(monkeypatch):
    monkeypatch.delenv("VODA_HWBENCH_ON_CPU")
    from vodascheduler_tpu.runtime.hwbench import run_hardware_bench
    with pytest.raises(RuntimeError, match="accelerator"):
        run_hardware_bench()


def test_two_point_differencing_cancels_overhead():
    """The two-point estimator must subtract fixed per-call overhead:
    feed it a fake timer where t(k) = C + k*s and check it returns s."""
    from vodascheduler_tpu.runtime import hwbench

    class FakeClock:
        def __init__(self):
            self.t = 0.0

        def __call__(self):
            return self.t

    clock = FakeClock()

    def make_scanned(k):
        def run():
            clock.t += 5.0 + 0.25 * k  # 5s overhead + 0.25s/iter
            return 0.0
        return run

    real_counter = hwbench.time.perf_counter
    real_fetch = hwbench._fetch
    hwbench.time.perf_counter = clock
    hwbench._fetch = lambda x: 0.0
    try:
        s = hwbench.time_per_iteration(make_scanned, k_small=2, k_big=10,
                                       reps=1)
    finally:
        hwbench.time.perf_counter = real_counter
        hwbench._fetch = real_fetch
    assert abs(s - 0.25) < 1e-9


def test_every_bench_point_has_flops_structure():
    """Config-rot guard: every model bench.py ships to the chip must
    have an analytic-FLOPs structure entry — in r5 a missing llama_1b
    entry burned the chip slot and surfaced as an unrelated XLA OOM
    from the retry path."""
    from vodascheduler_tpu.runtime.hwbench import _lm_structure

    bench = _bench_module()
    for model_name, _ in bench.HW_MODEL_POINTS:
        n_layers, d_model = _lm_structure(model_name)
        assert n_layers > 0 and d_model > 0, model_name


@pytest.mark.slow
def test_stream_main_emits_parseable_lines():
    """hwbench --stream (the subprocess mode bench.py drives) emits one
    JSON line per completed item; bench.parse_hw_stream rebuilds the
    section dict from them — including from a truncated tail."""
    import json
    import subprocess
    import sys

    env = dict(os.environ, VODA_HWBENCH_ON_CPU="1", JAX_PLATFORMS="cpu")
    kwargs = json.dumps({"model_points": [["llama_tiny", 2]],
                         "attention_points": [[1, 64]],
                         "moe_batch": None})
    res = subprocess.run(
        [sys.executable, "-m", "vodascheduler_tpu.runtime.hwbench",
         "--stream", kwargs],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert res.returncode == 0, res.stderr[-500:]

    parse_hw_stream = _bench_module().parse_hw_stream
    out = parse_hw_stream(res.stdout)
    assert out["models"][0]["model"] == "llama_tiny"
    assert out["attention"][0]["flash_ms"] > 0
    assert "peak_bf16_tflops_per_chip" in out

    # Kill-mid-write salvage: drop the last line's tail — earlier points
    # must survive.
    truncated = res.stdout[: res.stdout.rfind("{")]
    partial = parse_hw_stream(truncated)
    assert partial["models"][0]["model"] == "llama_tiny"


def _bench_module():
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    return bench


def _install_fake_hwbench(tmp_path, tail: str) -> None:
    """Stand in for the hwbench module under tmp_path: emit two points,
    then run `tail` (the scenario under test)."""
    import textwrap
    fake_pkg = tmp_path / "vodascheduler_tpu" / "runtime"
    fake_pkg.mkdir(parents=True)
    (tmp_path / "vodascheduler_tpu" / "__init__.py").write_text("")
    (fake_pkg / "__init__.py").write_text("")
    (fake_pkg / "hwbench.py").write_text(textwrap.dedent("""
        import json, sys, time
        print(json.dumps({"kind": "meta", "data": {"backend": "fake"}}),
              flush=True)
        print(json.dumps({"kind": "model", "data": {"model": "m1",
              "step_time_ms": 1.0}}), flush=True)
    """) + textwrap.dedent(tail))


def _watchdog_env(monkeypatch, timeout: str, stall: str) -> None:
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("VODA_HWBENCH_ON_CPU", "1")
    monkeypatch.setenv("VODA_BENCH_HW_TIMEOUT", timeout)
    monkeypatch.setenv("VODA_BENCH_HW_STALL_TIMEOUT", stall)
    monkeypatch.setenv("VODA_BENCH_HW_PROBE_TIMEOUT", "120")


def test_timeout_salvage_drains_flushed_lines(tmp_path, monkeypatch):
    """The wedge scenario end-to-end: the hwbench child flushes points,
    then hangs; maybe_hardware must kill it and keep every flushed point
    (Popen + post-kill drain — subprocess.run() discards the pipe on
    POSIX timeouts). Killed via the STALL watchdog with a 12s window:
    the stall clock does still run during child startup (last_line is
    initialized at Popen), so this is a margin bump, not immunity — the
    original 5s hard deadline flaked when slow startup under host load
    (a concurrent chip-attached capture) ate the whole budget before
    the two points landed; 12s of pure startup is far past anything
    observed."""
    bench = _bench_module()
    _install_fake_hwbench(tmp_path, "time.sleep(600)  # the wedge\n")
    _watchdog_env(monkeypatch, timeout="300", stall="12")
    _redirect_repo_dir(monkeypatch, bench, tmp_path)
    out = bench.maybe_hardware()
    assert out is not None
    assert out["models"] == [{"model": "m1", "step_time_ms": 1.0}]
    assert out["backend"] == "fake"
    # Specifically the STALL watchdog's message — the hard-deadline
    # branch has its own test below.
    assert "stalled" in out.get("error", ""), out


def test_hard_deadline_kills_still_streaming_child(tmp_path, monkeypatch):
    """The other watchdog: a child that never stalls (keeps flushing
    heartbeat lines) but runs past VODA_BENCH_HW_TIMEOUT must be killed
    by the hard deadline, keeping completed points. The 0.25s heartbeats
    pin the stall clock, so only the hard-deadline branch can fire — and
    the 15s deadline leaves 3× the startup margin that flaked at 5s."""
    bench = _bench_module()
    _install_fake_hwbench(tmp_path, """
        while True:  # never stalls, never finishes
            print(json.dumps({"kind": "tick", "data": {}}), flush=True)
            time.sleep(0.25)
    """)
    _watchdog_env(monkeypatch, timeout="15", stall="300")
    _redirect_repo_dir(monkeypatch, bench, tmp_path)
    out = bench.maybe_hardware()
    assert out is not None
    assert out["models"] == [{"model": "m1", "step_time_ms": 1.0}]
    assert out["backend"] == "fake"
    err = out.get("error", "")
    assert "exceeded 15s" in err and "killed" in err, out
    assert "stalled" not in err, out


def _redirect_repo_dir(monkeypatch, bench, tmp_path):
    """Make maybe_hardware see tmp_path as the repo root."""
    monkeypatch.setattr(bench.os.path, "dirname",
                        lambda p, _real=os.path.dirname: str(tmp_path)
                        if p == os.path.abspath(bench.__file__)
                        else _real(p))


def test_dead_tunnel_falls_back_to_cached_results(tmp_path, monkeypatch):
    """When the accelerator probe never succeeds (dead tunnel — the r3
    failure mode), maybe_hardware must emit the last-good cached results
    tagged cached_from, not a bare error marker."""
    import json

    bench = _bench_module()
    cached = {"backend": "tpu", "device_kind": "TPU v5 lite",
              "models": [{"model": "llama_350m", "mfu": 0.38}],
              "attention": []}
    (tmp_path / "doc").mkdir()
    (tmp_path / "doc" / "benchmarks_last_good.json").write_text(json.dumps(
        {"captured_at": "2026-07-30T05:30:00Z", "hardware": cached}))
    monkeypatch.setattr(bench, "_probe_backend",
                        lambda repo_dir: (None, "accelerator probe timed "
                                                "out (90s x3)"))
    _redirect_repo_dir(monkeypatch, bench, tmp_path)
    out = bench.maybe_hardware()
    assert out["models"] == cached["models"]
    assert out["cached_from"] == "2026-07-30T05:30:00Z"
    assert "timed out" in out["live_error"]


def test_dead_tunnel_without_cache_reports_error(tmp_path, monkeypatch):
    bench = _bench_module()
    monkeypatch.setattr(bench, "_probe_backend",
                        lambda repo_dir: (None, "probe died"))
    _redirect_repo_dir(monkeypatch, bench, tmp_path)
    out = bench.maybe_hardware()
    assert out == {"error": "probe died"}


def test_probe_retries_then_succeeds(monkeypatch, tmp_path):
    """_probe_backend must retry past transient flakes with backoff."""
    import subprocess
    import time
    import types

    bench = _bench_module()
    calls = {"n": 0}
    sleeps = []

    def fake_run(*a, **kw):
        calls["n"] += 1
        if calls["n"] < 3:
            raise subprocess.TimeoutExpired(cmd=a[0], timeout=kw["timeout"])
        return types.SimpleNamespace(returncode=0, stdout="cpu\n", stderr="")

    monkeypatch.setattr(subprocess, "run", fake_run)
    monkeypatch.setattr(time, "sleep", sleeps.append)
    backend, err = bench._probe_backend(str(tmp_path))
    assert backend == "cpu" and err is None
    assert calls["n"] == 3
    assert sleeps == [15, 30]  # backoff between attempts


def test_successful_run_writes_last_good_cache(tmp_path, monkeypatch):
    """A clean hardware run must refresh doc/benchmarks_last_good.json so
    the NEXT flaked round has something to fall back on."""
    import json

    bench = _bench_module()
    _install_fake_hwbench(tmp_path, "")  # clean exit after the points
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("VODA_HWBENCH_ON_CPU", "1")
    monkeypatch.setenv("VODA_BENCH_HW_TIMEOUT", "60")
    monkeypatch.setenv("VODA_BENCH_RESIZE", "0")  # fake tree has no module
    _redirect_repo_dir(monkeypatch, bench, tmp_path)
    out = bench.maybe_hardware()
    assert "error" not in out, out
    cache = json.loads(
        (tmp_path / "doc" / "benchmarks_last_good.json").read_text())
    assert cache["hardware"]["models"] == [{"model": "m1",
                                            "step_time_ms": 1.0}]
    assert cache["captured_at"]


def test_cache_write_drops_error_rows_and_keeps_prior_on_empty(tmp_path):
    """Per-row failures in ANY section (models/attention/moe/resize) must
    not become fallback evidence, and a run where every model point
    errored must not clobber a previously good cache with models: []."""
    import json

    bench = _bench_module()
    good = {"models": [{"model": "m1", "mfu": 0.4},
                       {"model": "m2", "error": "OOM"}],
            "attention": [{"batch": 8, "seq": 1024, "flash_ms": 1.0},
                          {"batch": 1, "seq": 8192, "error": "boom"}],
            "moe": {"error": "RESOURCE_EXHAUSTED: " + "x" * 50},
            "resize": [{"model": "m1", "resize_cost_seconds": 9.0},
                       {"model": "m2", "error": "died"}]}
    bench.write_last_good(str(tmp_path), good)
    cache = json.loads(
        (tmp_path / "doc" / "benchmarks_last_good.json").read_text())
    hw = cache["hardware"]
    assert hw["models"] == [{"model": "m1", "mfu": 0.4}]
    assert hw["attention"] == [{"batch": 8, "seq": 1024, "flash_ms": 1.0}]
    assert "moe" not in hw
    assert hw["resize"] == [{"model": "m1", "resize_cost_seconds": 9.0}]

    # Per-variant failure INSIDE the moe dict (e.g. gather_af) is
    # stripped while the measured variants stay.
    mixed_moe = {"models": [{"model": "m1", "mfu": 0.4}],
                 "moe": {"gather": {"step_time_ms": 1.0},
                         "dense_step_ms": 2.0,
                         "gather_af": {"error": "OOM"}}}
    bench.write_last_good(str(tmp_path), mixed_moe)
    cache_moe = json.loads(
        (tmp_path / "doc" / "benchmarks_last_good.json").read_text())
    assert cache_moe["hardware"]["moe"] == {"gather": {"step_time_ms": 1.0},
                                            "dense_step_ms": 2.0}

    # Every moe variant errored per-variant: the section is dropped, not
    # cached as an empty dict masquerading as a successful capture.
    all_moe_bad = {"models": [{"model": "m1", "mfu": 0.4}],
                   "moe": {"gather": {"error": "OOM"},
                           "gather_af": {"error": "OOM"}}}
    bench.write_last_good(str(tmp_path), all_moe_bad)
    cache_bad = json.loads(
        (tmp_path / "doc" / "benchmarks_last_good.json").read_text())
    assert "moe" not in cache_bad["hardware"]

    all_bad = {"models": [{"model": "m1", "error": "regression"}],
               "attention": [{"batch": 8, "seq": 1024, "flash_ms": 2.0}]}
    bench.write_last_good(str(tmp_path), all_bad)
    cache2 = json.loads(
        (tmp_path / "doc" / "benchmarks_last_good.json").read_text())
    assert cache2["hardware"]["models"] == [{"model": "m1", "mfu": 0.4}]
