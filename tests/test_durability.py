"""The durability plane (doc/durability.md): journal framing and
byte-level fault injection, snapshot + compaction (tombstones survive),
scheduler crash-recovery round trips, lease-based leader handover with
fencing epochs, and the kill -9 e2e."""

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from vodascheduler_tpu.allocator import ResourceAllocator
from vodascheduler_tpu.cluster.fake import FakeClusterBackend, WorkloadProfile
from vodascheduler_tpu.common.clock import VirtualClock
from vodascheduler_tpu.common.events import EventBus
from vodascheduler_tpu.common.job import JobConfig, JobSpec, TrainingJob
from vodascheduler_tpu.common.lifecycle import BookingLedger
from vodascheduler_tpu.common.store import JobStore
from vodascheduler_tpu.common.types import JobStatus
from vodascheduler_tpu.durability.journal import (
    FencedOut,
    FileStorage,
    Journal,
    JournalCorrupt,
    MemoryStorage,
    fsck,
    frame,
    parse_frames,
)
from vodascheduler_tpu.durability.leader import (
    FileLease,
    LeaseHeld,
    MemoryLease,
)
from vodascheduler_tpu.durability.recover import read_state
from vodascheduler_tpu.obs import audit as obs_audit
from vodascheduler_tpu.obs import tracer as obs_tracer
from vodascheduler_tpu.placement import PlacementManager
from vodascheduler_tpu.scheduler import Scheduler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---- world helpers ---------------------------------------------------------


def make_world(journal=None, hosts=2, chips=4, resume=False,
               clock=None, store=None, backend=None, bus=None,
               tracer=None):
    clock = clock or VirtualClock(start=1000.0)
    tracer = tracer or obs_tracer.Tracer(clock=clock, ring_size=256)
    store = store if store is not None else JobStore()
    bus = bus or EventBus()
    if backend is None:
        backend = FakeClusterBackend(clock, restart_overhead_seconds=2.0)
        for i in range(hosts):
            backend.add_host(f"host-{i}", chips, announce=False)
    pm = PlacementManager("p")
    sched = Scheduler("p", backend, store, ResourceAllocator(store),
                      clock, bus=bus, placement_manager=pm,
                      rate_limit_seconds=1.0, profile_cpu=False,
                      tracer=tracer, journal=journal, resume=resume)
    return clock, store, backend, bus, tracer, sched


def submit(sched, store, backend, clock, name, min_chips=1, max_chips=4,
           epochs=2):
    spec = JobSpec(name=name, pool="p",
                   config=JobConfig(min_num_chips=min_chips,
                                    max_num_chips=max_chips,
                                    epochs=epochs))
    backend.register_profile(name,
                             WorkloadProfile(epoch_seconds_at_1=8.0))
    store.insert_job(TrainingJob.from_spec(spec, submit_time=clock.now()))
    sched.create_training_job(name)


# ---- framing + byte-level fault injection ----------------------------------


class TestFraming:
    def test_round_trip(self):
        j = Journal(storage=MemoryStorage())
        for i in range(5):
            j.append("jbook", {"op": "commit", "job": f"j{i}", "chips": i})
        recs = j.records()
        assert [r["job"] for r in recs] == [f"j{i}" for i in range(5)]
        assert [r["seq"] for r in recs] == [1, 2, 3, 4, 5]
        assert all(r["epoch"] == 1 for r in recs)

    def test_unknown_kind_rejected_at_write(self):
        j = Journal(storage=MemoryStorage())
        with pytest.raises(ValueError, match="JOURNAL_KINDS"):
            j.append("not_a_kind", {})

    def test_torn_tail_dropped(self):
        s = MemoryStorage()
        j = Journal(storage=s)
        for i in range(3):
            j.append("jbook", {"op": "commit", "job": f"j{i}", "chips": 1})
        # Truncate mid-final-record — the crash artifact.
        s.data = s.data[: len(s.data) - 9]
        records, torn, corrupt = parse_frames(bytes(s.data))
        assert len(records) == 2 and torn == 1 and corrupt is None

    def test_duplicated_tail_record_deduplicated(self):
        s = MemoryStorage()
        j = Journal(storage=s)
        j.append("jbook", {"op": "commit", "job": "a", "chips": 2})
        j.append("jbook", {"op": "commit", "job": "b", "chips": 3})
        # Duplicate the last frame wholesale (a retried write).
        lines = bytes(s.data).split(b"\n")
        s.data.extend(lines[-2] + b"\n")
        state = read_state(Journal(storage=s))
        assert state.duplicate_records == 1
        assert state.booked == {"a": 2, "b": 3}

    def test_checksum_flip_on_tail_is_torn(self):
        s = MemoryStorage()
        j = Journal(storage=s)
        j.append("jbook", {"op": "commit", "job": "a", "chips": 2})
        j.append("jbook", {"op": "commit", "job": "b", "chips": 3})
        # Flip a payload byte of the FINAL record: checksum mismatch on
        # the tail == torn tail, dropped — a consistent prefix remains.
        s.data[-5] ^= 0x01
        state = read_state(Journal(storage=s))
        assert state.booked == {"a": 2}
        assert state.torn_tail >= 1

    def test_checksum_flip_mid_file_fails_loudly(self):
        s = MemoryStorage()
        j = Journal(storage=s)
        for i in range(4):
            j.append("jbook", {"op": "commit", "job": f"j{i}", "chips": 1})
        # Corrupt a payload byte of the FIRST record (valid records
        # follow): never silently resynchronized.
        first_nl = s.data.index(b"\n")
        s.data[first_nl - 3] ^= 0x01
        with pytest.raises(JournalCorrupt):
            Journal(storage=s).records()

    def test_reopen_trims_torn_tail_before_appending(self):
        """A restarted writer must truncate the crash's half-written
        frame, or its first append turns the torn tail into mid-file
        corruption."""
        s = MemoryStorage()
        j = Journal(storage=s)
        j.append("jbook", {"op": "commit", "job": "a", "chips": 2})
        j.append("jbook", {"op": "commit", "job": "b", "chips": 3})
        s.data = s.data[: len(s.data) - 7]  # torn tail
        j2 = Journal(storage=s, epoch=2)
        assert j2.torn_trimmed == 1
        j2.append("jbook", {"op": "commit", "job": "c", "chips": 1})
        state = read_state(j2)
        assert state.booked == {"a": 2, "c": 1}
        assert state.torn_tail == 1  # surfaced, never silent

    def test_file_fault_injection(self, tmp_path):
        """The same byte-level faults on a REAL file journal, through
        fsck (the `voda fsck` surface)."""
        path = str(tmp_path / "pool.wal")
        j = Journal(path=path)
        for i in range(5):
            j.append("jclock", {"job": f"j{i}", "at": float(i)})
        j.close()
        clean = fsck(path)
        assert clean["records"] == 5 and not clean["problems"]
        # Truncate mid-record.
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) - 6)
        report = fsck(path)
        assert report["records"] == 4
        assert report["torn_tail_count"] == 1
        assert not report["problems"]
        # Flip a checksum hex digit mid-file: loud.
        data = bytearray(open(path, "rb").read())
        second_sp = data.index(b" ", data.index(b" ") + 1)
        data[second_sp - 1] = ord("f") if data[second_sp - 1] != ord("f") \
            else ord("e")
        open(path, "wb").write(bytes(data))
        bad = fsck(path)
        assert any("corrupt" in p for p in bad["problems"])


# ---- the write-ahead seam --------------------------------------------------


class TestJournalingSeam:
    def test_ledger_mutations_replay(self):
        j = Journal(storage=MemoryStorage())
        ledger = BookingLedger(journal=j)
        ledger.commit("a", 4)
        ledger.commit_pass({"a": 2, "b": 3})
        ledger.release("b")
        state = read_state(j)
        assert state.booked == {"a": 2}
        assert state.granted == {"a", "b"}

    def test_commit_pass_is_delta_encoded(self):
        j = Journal(storage=MemoryStorage())
        ledger = BookingLedger(journal=j)
        ledger.commit_pass({f"j{i}": 1 for i in range(100)})
        before = j._appends
        ledger.commit_pass({**{f"j{i}": 1 for i in range(99)}, "j99": 2})
        assert j._appends == before + 1
        rec = j.records()[-1]
        assert rec["k"] == "jpass"
        assert rec["set"] == {"j99": 2} and rec["del"] == []

    def test_fenced_append_applies_nothing(self):
        """Append-before-apply: a deposed writer's mutation must not
        land in memory when its journal append is rejected."""
        lease = MemoryLease()
        j = Journal(storage=MemoryStorage(), epoch=lease.epoch,
                    fence=lease.current_epoch)
        ledger = BookingLedger(journal=j)
        ledger.commit("a", 4)
        lease.advance_epoch()
        with pytest.raises(FencedOut):
            ledger.commit("a", 2)
        assert ledger.get("a") == 4  # unchanged
        assert j.fenced
        # transition() likewise: status survives the fenced append.
        job = TrainingJob.from_spec(
            JobSpec(name="t", pool="p",
                    config=JobConfig(min_num_chips=1, max_num_chips=2,
                                     epochs=1)), submit_time=0.0)
        from vodascheduler_tpu.common import lifecycle
        with pytest.raises(FencedOut):
            lifecycle.transition(job, JobStatus.WAITING, reason="accepted",
                                 chips=0, journal=j)
        assert job.status == JobStatus.SUBMITTED


# ---- snapshot + compaction -------------------------------------------------


class TestCompaction:
    def test_compaction_preserves_state_and_bounds_replay(self):
        j = Journal(storage=MemoryStorage())
        for i in range(50):
            j.append("jbook", {"op": "commit", "job": "a", "chips": i + 1})
        before = read_state(j)
        assert j.maybe_compact(force=True)
        after = read_state(j)
        assert after.booked == before.booked == {"a": 50}
        assert after.granted == before.granted
        # Replay is now O(live): one jsnap marker in the segment.
        assert len(j.records()) == 1

    def test_seq_resumes_from_snapshot_after_lost_jsnap(self):
        """Crash in compaction's truncate window: snapshot written,
        segment emptied, the jsnap marker lost. The reopened journal
        must resume numbering PAST the snapshot's last_seq — restarting
        at 1 would make replay's seq dedup silently drop every
        post-restart record as a duplicate of the snapshot's range."""
        s = MemoryStorage()
        j = Journal(storage=s)
        for i in range(10):
            j.append("jbook", {"op": "commit", "job": "a", "chips": i + 1})
        j.maybe_compact(force=True)
        # Simulate the crash: drop the post-compaction segment (the
        # jsnap append never made it) — the snapshot alone survives.
        s.replace(b"")
        j2 = Journal(storage=s, epoch=2)
        assert j2._seq >= 10
        j2.append("jbook", {"op": "commit", "job": "NEW", "chips": 3})
        state = read_state(j2)
        assert state.booked == {"a": 10, "NEW": 3}
        assert state.duplicate_records == 0

    def test_delete_survives_crash_recover_compact_crash_recover(self):
        """The tombstone regression (doc/durability.md "Tombstones"):
        a deleted job must stay retired across crash -> recover ->
        compact -> crash -> recover — never resurrected."""
        storage = MemoryStorage()
        lease = MemoryLease()
        jnl = Journal(storage=storage, epoch=lease.epoch,
                      fence=lease.current_epoch)
        clock, store, backend, bus, tracer, sched = make_world(journal=jnl)
        submit(sched, store, backend, clock, "keep", epochs=1000)
        submit(sched, store, backend, clock, "victim", epochs=1000)
        clock.advance(5)
        sched.delete_training_job("victim")
        clock.advance(5)
        assert sched.done_jobs["victim"].status == JobStatus.CANCELED

        def crash_recover():
            sched_prev = crash_recover.sched
            sched_prev.stop()
            epoch = lease.advance_epoch()
            j2 = Journal(storage=storage, epoch=epoch,
                         fence=lease.current_epoch, clock=clock)
            _, _, _, _, _, s2 = make_world(
                journal=j2, resume=True, clock=clock, store=store,
                backend=backend, bus=bus, tracer=tracer)
            crash_recover.sched = s2
            return j2, s2

        crash_recover.sched = sched
        j2, s2 = crash_recover()
        assert "victim" not in s2.ready_jobs
        assert s2.done_jobs["victim"].status == JobStatus.CANCELED
        assert j2.maybe_compact(force=True)
        snap = j2.load_snapshot()
        assert snap["retired"].get("victim") == "Canceled"
        _, s3 = crash_recover()
        assert "victim" not in s3.ready_jobs
        assert s3.done_jobs["victim"].status == JobStatus.CANCELED
        assert "keep" in s3.ready_jobs
        assert s3.job_num_chips.get("victim", 0) == 0


# ---- scheduler crash recovery ----------------------------------------------


class TestCrashRecovery:
    def _crashed_world(self):
        storage = MemoryStorage()
        lease = MemoryLease()
        jnl = Journal(storage=storage, epoch=lease.epoch,
                      fence=lease.current_epoch)
        clock, store, backend, bus, tracer, sched = make_world(journal=jnl)
        for name in ("j0", "j1"):
            submit(sched, store, backend, clock, name)
        clock.advance(5)
        return storage, lease, clock, store, backend, bus, tracer, sched

    def _recover(self, storage, lease, clock, store, backend, bus, tracer):
        epoch = lease.advance_epoch()
        j2 = Journal(storage=storage, epoch=epoch,
                     fence=lease.current_epoch, clock=clock)
        return make_world(journal=j2, resume=True, clock=clock,
                          store=store, backend=backend, bus=bus,
                          tracer=tracer)[-1]

    def test_quiescent_recovery_is_exact(self):
        (storage, lease, clock, store, backend, bus, tracer,
         sched) = self._crashed_world()
        from vodascheduler_tpu.durability.recover import logical_tables
        pre = logical_tables(sched)
        sched.stop()
        s2 = self._recover(storage, lease, clock, store, backend, bus,
                           tracer)
        assert s2._recovered_tables == pre
        report = s2._last_recovery_report
        assert report["divergences"] == []
        assert not obs_audit.validate_record(report)
        assert s2.m_recovery_seconds.value() >= 0.0
        # And the recovered world still finishes its jobs.
        clock.advance(60)
        assert all(j.status == JobStatus.COMPLETED
                   for j in s2.done_jobs.values())

    def test_deposed_leader_writes_rejected(self):
        (storage, lease, clock, store, backend, bus, tracer,
         sched) = self._crashed_world()
        s2 = self._recover(storage, lease, clock, store, backend, bus,
                           tracer)
        with pytest.raises(FencedOut):
            sched.job_num_chips.commit("j0", 1)
        assert sched.journal.fenced
        # User-facing mutations on the deposed scheduler fail LOUDLY
        # (never ack-and-drop), and it stops itself.
        with pytest.raises(FencedOut, match="deposed"):
            sched.create_training_job("j0")
        with pytest.raises(FencedOut, match="deposed"):
            sched.delete_training_job("j0")
        assert sched._stopped
        # And replay never interleaves whatever a buggy writer landed.
        state = read_state(s2.journal)
        assert state.stale_records == 0

    def test_backend_lost_job_reconciled_and_audited(self):
        (storage, lease, clock, store, backend, bus, tracer,
         sched) = self._crashed_world()
        sched.stop()
        # The backend lost j0 behind the crashed scheduler's back.
        backend.stop_job("j0")
        s2 = self._recover(storage, lease, clock, store, backend, bus,
                           tracer)
        report = s2._last_recovery_report
        reasons = {(d["job"], d["reason"])
                   for d in report["divergences"]}
        assert ("j0", "backend_lost_job") in reasons
        # The AS-REBUILT tables (before the inline corrective pass):
        # j0 reconciled to WAITING with zero chips.
        booked, ready, _, _ = s2._recovered_tables
        assert dict(ready)["j0"] == "Waiting"
        assert dict(booked)["j0"] == 0
        # The corrective pass re-runs it to completion.
        clock.advance(80)
        assert s2.done_jobs["j0"].status == JobStatus.COMPLETED

    def test_admitted_but_unaccepted_job_never_lost(self):
        (storage, lease, clock, store, backend, bus, tracer,
         sched) = self._crashed_world()
        sched.stop()
        # Admitted to the durable store, but the CREATE event died with
        # the process: no journal trace.
        spec = JobSpec(name="late", pool="p",
                       config=JobConfig(min_num_chips=1, max_num_chips=2,
                                        epochs=1))
        backend.register_profile(
            "late", WorkloadProfile(epoch_seconds_at_1=8.0))
        store.insert_job(TrainingJob.from_spec(spec,
                                               submit_time=clock.now()))
        s2 = self._recover(storage, lease, clock, store, backend, bus,
                           tracer)
        assert "late" in s2.ready_jobs
        reasons = {(d["job"], d["reason"])
                   for d in s2._last_recovery_report["divergences"]}
        assert ("late", "unjournaled_job") in reasons
        clock.advance(60)
        assert s2.done_jobs["late"].status == JobStatus.COMPLETED

    def test_journal_stats_surface(self):
        (storage, lease, clock, store, backend, bus, tracer,
         sched) = self._crashed_world()
        stats = sched.journal_stats()
        assert stats["enabled"] and stats["records"] > 0
        assert stats["epoch"] == 1 and stats["torn_tail_count"] == 0
        sched.stop()
        s2 = self._recover(storage, lease, clock, store, backend, bus,
                           tracer)
        stats2 = s2.journal_stats()
        assert stats2["epoch"] == 2
        assert stats2["last_recovery"]["divergences"] == []
        # Journal-less schedulers answer honestly.
        _, _, _, _, _, bare = make_world()
        assert bare.journal_stats() == {"enabled": False}


# ---- leadership ------------------------------------------------------------


class TestLeadership:
    def test_file_lease_protocol(self, tmp_path):
        clock = VirtualClock(start=100.0)
        a = FileLease(str(tmp_path / "l"), holder="a", ttl_seconds=10.0,
                      clock=clock)
        b = FileLease(str(tmp_path / "l"), holder="b", ttl_seconds=10.0,
                      clock=clock)
        assert a.try_acquire() == 1
        with pytest.raises(LeaseHeld):
            b.try_acquire()
        assert a.renew()
        # a stops renewing; the lease expires; b takes over at epoch 2.
        clock.advance(11.0)
        assert b.try_acquire() == 2
        assert b.current_epoch() == 2
        assert not a.renew()  # deposed — and the file is NOT rewritten
        assert b.current_epoch() == 2
        # Clean release expires immediately: no TTL wait for the next.
        b.release()
        assert a.try_acquire() == 3

    def test_racing_takeovers_get_distinct_epochs(self, tmp_path):
        """Two standbys racing an expired lease must never both win
        with the SAME fencing epoch (the flock'd read-modify-write):
        the loser either sees LeaseHeld or lands a HIGHER epoch — a
        duplicate epoch would make both leaders pass every fence
        check."""
        import threading

        clock = VirtualClock(start=100.0)
        results = []
        barrier = threading.Barrier(4)

        def contender(name):
            lease = FileLease(str(tmp_path / "l"), holder=name,
                              ttl_seconds=10.0, clock=clock)
            barrier.wait()
            try:
                results.append((name, lease.try_acquire()))
            except LeaseHeld:
                results.append((name, None))

        threads = [threading.Thread(target=contender, args=(f"s{i}",),
                                    daemon=True) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        epochs = [e for _, e in results if e is not None]
        assert epochs, results
        assert len(set(epochs)) == len(epochs), \
            f"duplicate fencing epochs handed out: {results}"

    def test_leader_handover_e2e(self, tmp_path):
        """The acceptance e2e: standby takes over within one lease TTL
        of the leader going silent, recovers the journal, and the
        deposed leader's post-fencing appends are rejected."""
        clock = VirtualClock(start=1000.0)
        ttl = 10.0
        lease_a = FileLease(str(tmp_path / "lease"), holder="A",
                            ttl_seconds=ttl, clock=clock)
        lease_a.try_acquire()
        path = str(tmp_path / "pool.wal")
        jnl = Journal(path=path, epoch=lease_a.epoch,
                      fence=lease_a.current_epoch, clock=clock)
        lease_a.announce(jnl, op="acquire")
        _, store, backend, bus, tracer, sched_a = make_world(
            journal=jnl, clock=clock)
        submit(sched_a, store, backend, clock, "j0", epochs=1000)
        clock.advance(2)
        assert sched_a.ready_jobs["j0"].status == JobStatus.RUNNING
        died_at = clock.now()  # A goes silent (stops renewing)

        lease_b = FileLease(str(tmp_path / "lease"), holder="B",
                            ttl_seconds=ttl, clock=clock)
        with pytest.raises(LeaseHeld):
            lease_b.try_acquire()  # not expired yet
        clock.advance(ttl + 0.5)
        epoch = lease_b.try_acquire()
        assert epoch == 2
        assert clock.now() - died_at <= 2 * ttl  # within one TTL of expiry
        jnl_b = Journal(path=path, epoch=epoch,
                        fence=lease_b.current_epoch, clock=clock)
        lease_b.announce(jnl_b, op="acquire")
        _, _, _, _, _, sched_b = make_world(
            journal=jnl_b, resume=True, clock=clock, store=store,
            backend=backend, bus=bus, tracer=tracer)
        assert sched_b.ready_jobs["j0"].status == JobStatus.RUNNING
        assert sched_b._last_recovery_report["divergences"] == []
        # The deposed leader's append is rejected at the write.
        with pytest.raises(FencedOut):
            sched_a.journal.append("jclock", {"job": "j0", "at": 0.0})
        assert sched_a.journal.fenced
        # ...and the journal's epochs never regress.
        jnl_b.close()
        report = fsck(path)
        assert report["stale_epoch_count"] == 0
        assert not report["problems"]
        # Scheduling proceeds under B: the job keeps making progress.
        before = backend.job_progress("j0")
        clock.advance(60)
        assert backend.job_progress("j0") > before
        assert sched_b.ready_jobs["j0"].status == JobStatus.RUNNING


# ---- perf artifact pins ----------------------------------------------------


class TestPerfArtifactPins:
    def _baseline(self):
        with open(os.path.join(REPO, "doc", "perf_baseline.json")) as f:
            return json.load(f)

    def test_recovery_section_pinned(self):
        base = self._baseline()
        assert base["schema"] >= 7
        points = {p["n_jobs"]: p for p in base["recovery"]}
        assert 10000 in points
        p10k = points[10000]
        # The PR 8 decide target holds WITH journaling on.
        assert p10k["decide_wall_ms"]["p95"] < 50.0
        # Cold 10k recovery is pinned, sane, and divergence-free.
        assert 0.0 < p10k["recovery_seconds"] < 30.0
        assert p10k["recovery_divergences"] == 0
        assert p10k["recovered_jobs"] == 10000
        # Delta encoding holds: a steady-state churn pass appends a
        # bounded handful of records, not O(fleet).
        assert p10k["journal_appends_per_pass"] < 200


# ---- kill -9 e2e -----------------------------------------------------------


_CHILD = textwrap.dedent("""
    import os, sys, random
    sys.path.insert(0, {repo!r})
    from vodascheduler_tpu.allocator import ResourceAllocator
    from vodascheduler_tpu.cluster.fake import (FakeClusterBackend,
                                                WorkloadProfile)
    from vodascheduler_tpu.common.clock import VirtualClock
    from vodascheduler_tpu.common.events import EventBus
    from vodascheduler_tpu.common.job import JobConfig, JobSpec, TrainingJob
    from vodascheduler_tpu.common.store import FileJobStore
    from vodascheduler_tpu.durability.journal import Journal
    from vodascheduler_tpu.obs import tracer as obs_tracer
    from vodascheduler_tpu.placement import PlacementManager
    from vodascheduler_tpu.scheduler import Scheduler

    workdir = {workdir!r}
    clock = VirtualClock(start=1000.0)
    tracer = obs_tracer.Tracer(clock=clock, ring_size=64)
    store = FileJobStore(os.path.join(workdir, "state.json"))
    bus = EventBus()
    backend = FakeClusterBackend(clock, restart_overhead_seconds=2.0)
    for i in range(4):
        backend.add_host(f"host-{{i}}", 4, announce=False)
    jnl = Journal(path=os.path.join(workdir, "pool.wal"), clock=clock)
    sched = Scheduler("p", backend, store, ResourceAllocator(store),
                      clock, bus=bus,
                      placement_manager=PlacementManager("p"),
                      rate_limit_seconds=1.0, profile_cpu=False,
                      tracer=tracer, journal=jnl)
    rng = random.Random(7)
    i = 0
    while True:  # event storm until killed
        name = f"storm-{{i:04d}}"
        spec = JobSpec(name=name, pool="p",
                       config=JobConfig(min_num_chips=1,
                                        max_num_chips=rng.choice((1, 2, 4)),
                                        epochs=3))
        backend.register_profile(
            name, WorkloadProfile(epoch_seconds_at_1=8.0))
        store.insert_job(TrainingJob.from_spec(spec,
                                               submit_time=clock.now()))
        sched.create_training_job(name)
        if rng.random() < 0.3 and sched.ready_jobs:
            sched.delete_training_job(
                rng.choice(sorted(sched.ready_jobs)))
        clock.advance(rng.choice((0.2, 1.5, 3.0)))
        i += 1
        if i == 5:
            print("STORMING", flush=True)
""")


@pytest.mark.slow
class TestKillNineE2E:
    def test_kill9_mid_storm_recovers_committed_prefix(self, tmp_path):
        """kill -9 an in-flight scheduler under an event storm; restart;
        the recovered state must be exactly what the journal's committed
        prefix + the (dead) backend's view dictate: every admitted
        non-retired job present, nothing double-booked, nothing lost."""
        workdir = str(tmp_path)
        child = subprocess.Popen(
            [sys.executable, "-c",
             _CHILD.format(repo=REPO, workdir=workdir)],
            stdout=subprocess.PIPE, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert child.stdout.readline().strip() == "STORMING"
        time.sleep(0.7)  # mid-flight, whatever it is doing
        os.kill(child.pid, signal.SIGKILL)
        child.wait(timeout=30)

        from vodascheduler_tpu.common.store import FileJobStore
        clock = VirtualClock(start=2000.0)
        store = FileJobStore(os.path.join(workdir, "state.json"))
        # The committed prefix, parsed INDEPENDENTLY of recovery.
        expected = read_state(Journal(path=os.path.join(workdir,
                                                        "pool.wal"),
                                      clock=clock))
        assert expected.records > 0
        # Determinism: a second independent replay of the same bytes
        # reads byte-identical state (before recovery appends anything).
        again = read_state(Journal(path=os.path.join(workdir, "pool.wal"),
                                   clock=clock))
        assert (again.statuses, again.booked, again.retired,
                again.last_seq) == (expected.statuses, expected.booked,
                                    expected.retired, expected.last_seq)
        jnl = Journal(path=os.path.join(workdir, "pool.wal"),
                      epoch=expected.epoch + 1, clock=clock)
        # A fresh backend: the fake cluster died with the process, so
        # every journal-RUNNING job must reconcile to backend_lost.
        _, _, backend, bus, tracer, sched = make_world(
            journal=jnl, clock=clock, store=store, hosts=4)
        from vodascheduler_tpu.durability.recover import recover_scheduler
        report = recover_scheduler(sched)

        # Byte-identical to the committed prefix (the AS-REBUILT tables,
        # before the inline corrective pass re-grants anything): every
        # journal-known, non-retired job is back, reconciled against the
        # dead backend to WAITING/0; every retired job stays retired;
        # every store-admitted job the journal never saw is re-accepted.
        booked_t, ready_t, done_t, _ = sched._recovered_tables
        booked, ready = dict(booked_t), dict(ready_t)
        done = dict(done_t)
        for name, status in expected.statuses.items():
            assert name in ready, f"lost journaled job {name}"
            assert ready[name] == "Waiting"
            assert booked.get(name, 0) == 0
        for name in expected.retired:
            assert name not in ready
            assert name in done
        for job in store.list_jobs(pool="p"):
            if job.name in expected.retired:
                continue
            assert job.name in ready, f"lost admitted job {job.name}"
        # No double booking, trivially: the dead backend freed all.
        assert sum(booked.values()) == 0
        lost = {d["job"] for d in report["divergences"]
                if d["reason"] == "backend_lost_job"}
        # Every job the journal had RUNNING — or booked > 0 (the kill
        # can land mid-pass, between the booking commit and the start
        # transition) — reconciles as backend_lost against the dead
        # backend; nothing else does.
        expected_lost = {n for n, s in expected.statuses.items()
                         if s == "Running"}
        expected_lost |= {n for n, b in expected.booked.items() if b > 0}
        assert lost == expected_lost - set(expected.retired)
