"""Real-data training + the convergence-across-resize proof.

The core correctness claim of the whole elastic design — "checkpoint-
restart resize preserves training" — needs real data to mean anything:
optimizer state, LR-schedule position, and data position must all come
back. The reference demonstrates it live with Keras MNIST + Elastic
Horovod (reference: examples/py/tensorflow2/
tensorflow2_keras_mnist_elastic.py:100-150); here it is a hermetic test
on the 8-device CPU mesh with the bundled UCI digits data.
"""

import os

import jax
import numpy as np
import pytest

from vodascheduler_tpu.data import eval_classifier, load_digits_dataset
from vodascheduler_tpu.models import get_model
from vodascheduler_tpu.runtime.train import TrainSession

SEED = 7
BATCH = 64
LR = 3e-3


def test_digits_dataset_is_real_and_split_deterministically():
    ds = load_digits_dataset()
    ds2 = load_digits_dataset()
    assert ds is ds2  # cached
    assert ds.num_train + ds.test_x.shape[0] == 1797  # the real UCI set
    assert ds.num_classes == 10
    assert ds.train_x.dtype == np.float32
    assert 0.0 <= ds.train_x.min() and ds.train_x.max() <= 1.0
    # Real images are not noise: class-conditional pixel means separate.
    m0 = ds.train_x[ds.train_y == 0].mean(axis=0)
    m1 = ds.train_x[ds.train_y == 1].mean(axis=0)
    assert np.abs(m0 - m1).max() > 0.3


def test_batch_stream_is_pure_function_of_key():
    """Restart-stability precondition: the batch depends only on the rng
    key (not device count / call order), so a restored rng resumes the
    stream exactly."""
    bundle = get_model("digits_mlp")
    key = jax.random.PRNGKey(123)
    a = bundle.make_batch(16, key)
    b = bundle.make_batch(16, key)
    np.testing.assert_array_equal(np.asarray(a["images"]),
                                  np.asarray(b["images"]))
    np.testing.assert_array_equal(np.asarray(a["labels"]),
                                  np.asarray(b["labels"]))
    c = bundle.make_batch(16, jax.random.PRNGKey(124))
    assert not np.array_equal(np.asarray(a["labels"]),
                              np.asarray(c["labels"]))


def _eval(bundle, params, ds):
    return eval_classifier(
        lambda p, x: bundle.module.apply({"params": p}, x), params, ds)


def test_training_survives_resize_on_real_data(tmp_path):
    """Train K steps straight vs. K steps with a forced mid-run resize
    (1 -> 2 devices, checkpoint-restart-reshard); both must converge to
    the same model: optimizer moments, Adam step count, and the data
    stream (the checkpointed rng) all restored.

    The runs see IDENTICAL global batches (the stream is keyed by the
    restored rng), so the only permitted divergence is cross-device
    reduction order — tolerance reflects that, not model noise."""
    ds = load_digits_dataset()
    bundle = get_model("digits_mlp")
    total, half = 40, 20

    straight = TrainSession(bundle, 1, devices=jax.devices()[:1],
                            global_batch_size=BATCH, seed=SEED,
                            learning_rate=LR)
    straight.run_steps(total)
    ev_straight = _eval(bundle, straight.state["params"], ds)

    resized = TrainSession(bundle, 1, devices=jax.devices()[:1],
                           global_batch_size=BATCH, seed=SEED,
                           learning_rate=LR)
    resized.run_steps(half)
    ckpt_dir = os.fspath(tmp_path / "ckpt")
    resized.save(ckpt_dir)
    resized.finish_saves()
    del resized

    resumed = TrainSession.resume(bundle, 2, ckpt_dir,
                                  devices=jax.devices()[:2],
                                  global_batch_size=BATCH,
                                  learning_rate=LR)
    assert resumed.step == half
    resumed.run_steps(total - half)
    assert resumed.step == total
    ev_resumed = _eval(bundle, resumed.state["params"], ds)

    # Both genuinely converged on held-out real data...
    assert ev_straight["accuracy"] > 0.88, ev_straight
    assert ev_resumed["accuracy"] > 0.88, ev_resumed
    # ...and to the SAME model (reduction-order noise only).
    assert abs(ev_straight["loss"] - ev_resumed["loss"]) < 1e-3, (
        ev_straight, ev_resumed)
    max_param_diff = max(
        float(np.abs(np.asarray(a) - np.asarray(b)).max())
        for a, b in zip(jax.tree.leaves(straight.state["params"]),
                        jax.tree.leaves(resumed.state["params"])))
    assert max_param_diff < 1e-2, max_param_diff
    # Adam's schedule position survived: step counts in the optimizer
    # state match the uninterrupted run.
    assert int(resumed.state["step"]) == int(straight.state["step"])


def test_text_corpus_is_real_prose_and_deterministic():
    from vodascheduler_tpu.data import load_text_corpus

    c = load_text_corpus()
    assert c.train.size > 400_000
    assert c.test.size > 10_000
    text = bytes(c.train[:200_000]).decode("utf-8", errors="replace")
    # Real English prose, not noise: common words appear often.
    assert text.count(" the ") > 200
    assert load_text_corpus() is c  # cached


def test_text_batch_stream_is_pure_function_of_key():
    bundle = get_model("llama_tiny_text")
    key = jax.random.PRNGKey(5)
    a, b = bundle.make_batch(8, key), bundle.make_batch(8, key)
    np.testing.assert_array_equal(np.asarray(a["inputs"]),
                                  np.asarray(b["inputs"]))
    # Targets are inputs shifted by one (next-byte LM).
    np.testing.assert_array_equal(np.asarray(a["inputs"][:, 1:]),
                                  np.asarray(a["targets"][:, :-1]))
    assert int(a["inputs"].max()) < 256


@pytest.mark.slow  # ~80 training steps on CPU
def test_byte_lm_learns_real_text():
    """The LM-family convergence evidence: loss on real prose falls well
    below the uniform-byte floor (ln 256 ≈ 5.55) within ~80 steps."""
    bundle = get_model("llama_tiny_text")
    s = TrainSession(bundle, 2, devices=jax.devices()[:2],
                     global_batch_size=16, seed=1, learning_rate=3e-3)
    first = s.run_steps(5)
    # Already below the uniform floor (ln 256 ≈ 5.55): byte frequencies
    # are learned within a handful of steps.
    assert 3.8 < first < 5.6, first
    last = s.run_steps(75)
    assert last < 3.6, last  # real structure learned, not just frequencies


@pytest.mark.slow  # two subprocess legs, each importing jax (~40 s)
@pytest.mark.parametrize("model", ["digits_mlp"])
def test_real_data_example_script_smoke(tmp_path, model):
    """The runnable example (examples/jax/digits_real_data_elastic.py)
    completes a short elastic run — resume included — on CPU devices."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               VODA_FORCE_CPU_DEVICES="2")
    script = os.path.join(repo, "examples", "jax",
                          "digits_real_data_elastic.py")
    # Leg 1: one "epoch" at 1 chip, then exit (epochs-limited run).
    r1 = subprocess.run(
        [sys.executable, script, "--num-chips", "1", "--epochs", "1",
         "--steps-per-epoch", "10", "--workdir", os.fspath(tmp_path)],
        capture_output=True, text=True, timeout=420, env=env, cwd=repo)
    assert r1.returncode == 0, r1.stderr[-800:]
    assert "accuracy" in r1.stdout
    # Leg 2: resized to 2 chips, resumes from the checkpoint and finishes.
    r2 = subprocess.run(
        [sys.executable, script, "--num-chips", "2", "--epochs", "2",
         "--steps-per-epoch", "10", "--workdir", os.fspath(tmp_path)],
        capture_output=True, text=True, timeout=420, env=env, cwd=repo)
    assert r2.returncode == 0, r2.stderr[-800:]
    assert "resumed at step 10" in r2.stdout, r2.stdout
