"""Fractional sub-host sharing (doc/fractional-sharing.md): resource
classes, within-block feasibility, the whole-host baseline's footprint
accounting, interference-sensitive physics and placement pricing, the
audit/CLI surfacing, and the committed perf-baseline pin."""

import json
import os

import pytest

from vodascheduler_tpu.allocator import (
    AllocationRequest,
    ResourceAllocator,
)
from vodascheduler_tpu.allocator.allocator import (
    enforce_feasibility,
    enforce_feasibility_reference,
    feasibility_self_check,
)
from vodascheduler_tpu.cluster.fake import FakeClusterBackend, WorkloadProfile
from vodascheduler_tpu.common.clock import VirtualClock
from vodascheduler_tpu.common.events import EventBus
from vodascheduler_tpu.common.job import (
    JobConfig,
    JobSpec,
    TrainingJob,
    resolve_resource_class,
)
from vodascheduler_tpu.common.store import JobStore
from vodascheduler_tpu.obs import audit as obs_audit
from vodascheduler_tpu.placement import PlacementManager, PoolTopology
from vodascheduler_tpu.placement.topology import default_pool
from vodascheduler_tpu.scheduler import Scheduler
from vodascheduler_tpu.service import AdmissionService

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TOPO = PoolTopology(torus_dims=(4, 4, 4), host_block=(2, 2, 1))  # cph=4


def job(name, lo, hi, rc="auto", submit=0.0):
    spec = JobSpec(name=name, resource_class=rc,
                   config=JobConfig(min_num_chips=lo, max_num_chips=hi))
    return TrainingJob.from_spec(spec, submit_time=submit)


class TestResourceClass:
    def test_auto_resolves_by_host_block(self):
        assert resolve_resource_class("auto", 2, 4) == "fractional"
        assert resolve_resource_class("auto", 3, 4) == "fractional"
        assert resolve_resource_class("auto", 4, 4) == "whole_host"
        assert resolve_resource_class("auto", 16, 4) == "whole_host"

    def test_explicit_class_wins(self):
        assert resolve_resource_class("fractional", 16, 4) == "fractional"
        assert resolve_resource_class("whole_host", 2, 4) == "whole_host"

    def test_spec_roundtrip_carries_class(self):
        s = JobSpec(name="x", resource_class="fractional")
        assert JobSpec.from_dict(s.to_dict()).resource_class == "fractional"
        # Old stored specs predate the field: default is auto.
        d = s.to_dict()
        del d["resource_class"]
        assert JobSpec.from_dict(d).resource_class == "auto"


class TestFractionalFeasibility:
    def test_any_sub_host_count_is_a_partition(self):
        from vodascheduler_tpu.placement.topology import (
            is_feasible_count,
            next_feasible_above,
            round_to_feasible,
        )
        # Classic rules: 3 has no sub-block shape on (2,2,1).
        assert not is_feasible_count(3, TOPO)
        # Fractional: every 1..cph-1 count partitions a host block.
        for n in (1, 2, 3):
            assert is_feasible_count(n, TOPO, fractional=True)
        assert round_to_feasible(3, TOPO, fractional=True) == 3
        assert round_to_feasible(3, TOPO) == 2
        assert next_feasible_above(2, TOPO, fractional=True) == 3
        # At and above one host the whole-host table applies unchanged.
        assert is_feasible_count(4, TOPO, fractional=True)
        assert not is_feasible_count(5, TOPO, fractional=True)
        assert not is_feasible_count(5, TOPO)

    def test_table_matches_scan_oracles(self):
        from vodascheduler_tpu.placement.topology import (
            _is_feasible_scan,
            _next_feasible_above_scan,
            _round_to_feasible_scan,
            is_feasible_count,
            next_feasible_above,
            round_to_feasible,
        )
        for topo in (TOPO, default_pool(4, 8),
                     PoolTopology((8, 4, 4), (2, 2, 2))):
            for frac in (False, True):
                for n in range(0, topo.total_chips + 2):
                    assert is_feasible_count(n, topo, fractional=frac) == \
                        _is_feasible_scan(n, topo, frac), (topo, frac, n)
                    assert round_to_feasible(n, topo, fractional=frac) == \
                        _round_to_feasible_scan(n, topo, frac)
                    assert next_feasible_above(n, topo, fractional=frac) \
                        == _next_feasible_above_scan(n, topo, frac)

    def test_enforce_differential_oracle_clean(self):
        # The seeded mixed-class differential sweep (also wired into
        # `make modelcheck-selftest`): table == scan, values AND dict
        # order, both sharing modes.
        assert feasibility_self_check(n_pools=40) == []


class TestWholeHostBaseline:
    def test_footprint_charges_whole_hosts(self):
        # 4 fractional 2-chip jobs on a 2-host (8-chip) pool: sharing
        # fits all 4; the whole-host baseline fits only 2 (each grant's
        # footprint is a 4-chip host).
        topo = PoolTopology(torus_dims=(4, 2), host_block=(2, 2))  # 2 hosts
        jobs = [job(f"f{i}", 1, 2) for i in range(4)]
        grants = {f"f{i}": 2 for i in range(4)}
        shared = enforce_feasibility(dict(grants), jobs, 8, topo,
                                     fractional_sharing=True)
        assert shared == grants
        exclusive = enforce_feasibility(dict(grants), jobs, 8, topo,
                                        fractional_sharing=False)
        assert exclusive == {"f0": 2, "f1": 2, "f2": 0, "f3": 0}
        # The scan-based oracle agrees exactly.
        assert exclusive == enforce_feasibility_reference(
            dict(grants), jobs, 8, topo, fractional_sharing=False)

    def test_sharing_off_gives_sub_host_jobs_exclusive_hosts(self):
        clock = VirtualClock(start=1753760000.0)
        store, bus = JobStore(), EventBus()
        backend = FakeClusterBackend(clock)
        topo = default_pool(2, 4)
        for c in topo.host_coords():
            backend.add_host(topo.host_name(c), topo.chips_per_host,
                             announce=False)
        backend.set_topology(topo)
        pm = PlacementManager("pool", topology=topo)
        sched = Scheduler("pool", backend, store, ResourceAllocator(store),
                          clock, bus=bus, placement_manager=pm,
                          algorithm="ElasticFIFO", rate_limit_seconds=1.0,
                          fractional_sharing=False)
        admission = AdmissionService(store, bus, clock)
        a = admission.create_training_job(
            JobSpec(name="tiny-a", pool="pool",
                    config=JobConfig(min_num_chips=1, max_num_chips=2,
                                     epochs=100)))
        clock.advance(2.0)
        b = admission.create_training_job(
            JobSpec(name="tiny-b", pool="pool",
                    config=JobConfig(min_num_chips=1, max_num_chips=2,
                                     epochs=100)))
        clock.advance(2.0)
        # Both run 2 chips, but each occupies a FULL exclusive host.
        assert sched.job_num_chips[a] == 2
        assert sched.job_num_chips[b] == 2
        hosts_a = {hs.host for hs in pm.job_placements[a].host_slots}
        hosts_b = {hs.host for hs in pm.job_placements[b].host_slots}
        assert hosts_a and hosts_b and hosts_a.isdisjoint(hosts_b)
        assert pm.job_placements[a].num_workers == 4  # footprint slots
        assert pm.cotenant_host_count() == 0
        assert all(h.free_slots == 0 for h in pm.host_states.values())

    def test_sharing_on_cotenants_one_host(self):
        clock = VirtualClock(start=1753760000.0)
        store, bus = JobStore(), EventBus()
        backend = FakeClusterBackend(clock)
        topo = default_pool(2, 4)
        for c in topo.host_coords():
            backend.add_host(topo.host_name(c), topo.chips_per_host,
                             announce=False)
        backend.set_topology(topo)
        pm = PlacementManager("pool", topology=topo)
        sched = Scheduler("pool", backend, store, ResourceAllocator(store),
                          clock, bus=bus, placement_manager=pm,
                          algorithm="ElasticFIFO", rate_limit_seconds=1.0,
                          fractional_sharing=True)
        admission = AdmissionService(store, bus, clock)
        for n in ("co-a", "co-b"):
            admission.create_training_job(
                JobSpec(name=n, pool="pool",
                        config=JobConfig(min_num_chips=2, max_num_chips=2,
                                         epochs=100)))
            clock.advance(2.0)
        # Best-fit packs both 2-chip tenants onto ONE shared host.
        assert pm.cotenant_host_count() == 1


class TestInterferencePhysics:
    def _backend(self):
        clock = VirtualClock(start=0.0)
        backend = FakeClusterBackend(clock, restart_overhead_seconds=0.0)
        topo = default_pool(2, 4)
        for c in topo.host_coords():
            backend.add_host(topo.host_name(c), 4, announce=False)
        backend.set_topology(topo)
        return clock, backend

    def _spec(self, name, chips=2):
        return JobSpec(name=name,
                       config=JobConfig(min_num_chips=chips,
                                        max_num_chips=chips, epochs=1000))

    def test_cotenant_pays_interference(self):
        clock, backend = self._backend()
        profile = WorkloadProfile(epoch_seconds_at_1=100.0,
                                  speedup={2: 2.0},
                                  interference_fraction=0.2)
        backend.register_profile("a", profile)
        backend.register_profile("b", profile)
        backend.start_job(self._spec("a"), 2, [("host-0", 2)])
        backend.start_job(self._spec("b"), 2, [("host-0", 2)])
        with backend._state_lock:
            sa, sb = backend.jobs["a"], backend.jobs["b"]
            assert sa.cotenancy == pytest.approx(0.5)
            assert backend._effective_speedup(sa) == pytest.approx(
                2.0 * (1 - 0.2 * 0.5))
        clock.advance(10.0)
        backend.sync_accounting()
        assert backend.interference_penalty_chip_seconds > 0.0
        # Tenant b stops: a's rate recovers and its timers re-arm.
        backend.stop_job("b")
        with backend._state_lock:
            assert sa.cotenancy == 0.0
            assert backend._effective_speedup(sa) == pytest.approx(2.0)

    def test_exclusive_hosts_interfere_not(self):
        clock, backend = self._backend()
        profile = WorkloadProfile(epoch_seconds_at_1=100.0,
                                  interference_fraction=0.2)
        backend.register_profile("a", profile)
        backend.register_profile("b", profile)
        backend.start_job(self._spec("a"), 2, [("host-0", 2)])
        backend.start_job(self._spec("b"), 2, [("host-1", 2)])
        clock.advance(10.0)
        backend.sync_accounting()
        assert backend.interference_penalty_chip_seconds == 0.0

    def test_no_topology_keeps_prefractional_physics(self):
        clock = VirtualClock(start=0.0)
        backend = FakeClusterBackend(clock)
        backend.add_host("host-0", 4, announce=False)
        backend.register_profile("a", WorkloadProfile(
            interference_fraction=0.5))
        backend.register_profile("b", WorkloadProfile(
            interference_fraction=0.5))
        backend.start_job(self._spec("a"), 2, [("host-0", 2)])
        backend.start_job(self._spec("b"), 2, [("host-0", 2)])
        with backend._state_lock:
            assert backend.jobs["a"].cotenancy == 0.0


class TestInterferencePricing:
    def test_weighted_pick_prefers_least_cotenanted_host(self):
        pm = PlacementManager("pool", topology=default_pool(3, 4))
        for h in ("host-0", "host-1", "host-2"):
            pm.add_host(h, 4)
        # host-0 half-occupied by a stranger; host-1 empty.
        pm.set_interference_weights({})
        pm.place({"big": 2})
        assert [hs.host for hs
                in pm.job_placements["big"].host_slots] == ["host-0"]
        # Unweighted pick: tightest fit -> co-tenant with `big`.
        pm.place({"big": 2, "plain": 2})
        assert [hs.host for hs
                in pm.job_placements["plain"].host_slots] == ["host-0"]
        # Weighted (fractional) pick: the least-co-tenanted host wins.
        pm.set_interference_weights({"frac": 4})
        pm.place({"big": 2, "plain": 2, "frac": 2})
        assert [hs.host for hs
                in pm.job_placements["frac"].host_slots] == ["host-1"]

    def test_fractional_stats_surface_co_tenancy(self):
        pm = PlacementManager("pool", topology=default_pool(2, 4))
        pm.add_host("host-0", 4)
        pm.add_host("host-1", 4)
        pm.set_interference_weights({"frac": 3})
        pm.place({"whole": 2, "frac": 2})
        stats = pm.job_fractional_stats("frac")
        # frac took the empty host (interference-priced pick).
        assert stats is not None
        assert stats["partition"] == 2
        assert pm.job_fractional_stats("whole") is None  # no weight
        fleet = pm.fractional_fleet_stats()
        assert fleet["fractional_jobs"] == 1
        # Force co-tenancy: a third job fills the remaining slots.
        pm.place({"whole": 2, "frac": 2, "extra": 4})
        stats = pm.job_fractional_stats("frac")
        assert stats["co_tenants"], stats
        assert stats["interference_price"] > 0


class TestAuditAndCli:
    def _world(self):
        clock = VirtualClock(start=1753760000.0)
        store, bus = JobStore(), EventBus()
        backend = FakeClusterBackend(clock)
        topo = default_pool(2, 4)
        for c in topo.host_coords():
            backend.add_host(topo.host_name(c), 4, announce=False)
        backend.set_topology(topo)
        pm = PlacementManager("pool", topology=topo)
        sched = Scheduler("pool", backend, store, ResourceAllocator(store),
                          clock, bus=bus, placement_manager=pm,
                          algorithm="ElasticFIFO", rate_limit_seconds=1.0)
        return clock, store, backend, sched, AdmissionService(
            store, bus, clock)

    def test_fractional_delta_block_emitted_and_valid(self):
        clock, store, backend, sched, admission = self._world()
        # resnet50 category: a nonzero interference weight
        # (FAMILY_INTERFERENCE) — the block only renders for weighted
        # fractional tenants.
        name = admission.create_training_job(
            JobSpec(name="resnet50", pool="pool",
                    config=JobConfig(min_num_chips=1, max_num_chips=2,
                                     epochs=100)))
        clock.advance(2.0)
        recs = sched.audit_records(5)
        deltas = {d["job"]: d for r in recs for d in r["deltas"]}
        frac = deltas[name].get("fractional")
        assert frac is not None
        assert frac["partition"] == 2
        assert frac["hosts"]
        assert frac["co_tenants"] == []
        for rec in recs:
            assert obs_audit.validate_record(rec) == []

    def test_validator_rejects_malformed_fractional_block(self):
        rec = {
            "kind": "resched_audit", "schema": 1, "ts": 0.0,
            "pool": "p", "seq": 1, "trace_id": "t", "triggers": ["manual"],
            "algorithm": "ElasticFIFO", "total_chips": 8, "queue": [],
            "duration_ms": 1.0,
            "deltas": [{"job": "j", "before": 0, "after": 2,
                        "reasons": ["started"],
                        "fractional": {"partition": 2}}],
        }
        problems = obs_audit.validate_record(rec)
        assert any("fractional block missing" in p for p in problems)
        rec["deltas"][0]["fractional"] = {
            "partition": 2, "hosts": [], "co_tenants": [],
            "interference_price": 0, "vibes": 1}
        problems = obs_audit.validate_record(rec)
        assert any("unknown fractional field" in p for p in problems)

    def test_explain_and_top_render_fractional(self, capsys):
        from vodascheduler_tpu import cli
        clock, store, backend, sched, admission = self._world()
        name = admission.create_training_job(
            JobSpec(name="resnet50", pool="pool",
                    config=JobConfig(min_num_chips=1, max_num_chips=2,
                                     epochs=100)))
        clock.advance(2.0)
        cli._print_explain(name, {"records": sched.explain_job(name)})
        out = capsys.readouterr().out
        assert "fractional[" in out
        cli._print_top(sched.profile_records(0))
        out = capsys.readouterr().out
        assert "fractional: jobs=" in out

    def test_fractional_jobs_gauge(self):
        clock, store, backend, sched, admission = self._world()
        admission.create_training_job(
            JobSpec(name="tiny", pool="pool",
                    config=JobConfig(min_num_chips=1, max_num_chips=2,
                                     epochs=100)))
        admission.create_training_job(
            JobSpec(name="big", pool="pool",
                    config=JobConfig(min_num_chips=4, max_num_chips=8,
                                     epochs=100)))
        clock.advance(2.0)
        exposition = sched.registry.exposition()
        assert 'voda_scheduler_fractional_jobs{pool="pool"} 1' in exposition


class TestHysteresisFractionalBypass:
    def test_sub_host_grow_within_partition_bypasses(self):
        clock = VirtualClock(start=1753760000.0)
        store, bus = JobStore(), EventBus()
        backend = FakeClusterBackend(clock)
        # No Tier-A support: the classic grow_fits_host bypass is off
        # the table, so only the fractional gate can wave this through.
        backend.supports_inplace_resize = False
        topo = default_pool(1, 4)  # ONE 4-chip host: true sub-host life
        for c in topo.host_coords():
            backend.add_host(topo.host_name(c), 4, announce=False)
        backend.set_topology(topo)
        pm = PlacementManager("pool", topology=topo)
        sched = Scheduler("pool", backend, store, ResourceAllocator(store),
                          clock, bus=bus, placement_manager=pm,
                          algorithm="ElasticFIFO", rate_limit_seconds=1.0,
                          scale_out_hysteresis=2.0,
                          resize_cooldown_seconds=600.0)
        admission = AdmissionService(store, bus, clock)
        # grower starts at 3 (leftover), shrinks to 2 when tiny arrives
        # (scale-ins are not gated), then grows 2 -> 3 inside the
        # cooldown window when tiny leaves: the gate fires, and the
        # target stays a sub-host partition of its own host block.
        a = admission.create_training_job(
            JobSpec(name="grower", pool="pool",
                    config=JobConfig(min_num_chips=1, max_num_chips=3,
                                     epochs=10000)))
        clock.advance(2.0)
        b = admission.create_training_job(
            JobSpec(name="tiny", pool="pool",
                    config=JobConfig(min_num_chips=2, max_num_chips=2,
                                     epochs=2)))
        clock.advance(2.0)
        assert sched.job_num_chips[a] == 2
        assert sched.job_num_chips[b] == 2
        admission.delete_training_job(b)
        clock.advance(5.0)
        # The delete's own pass still saw tiny's slots held (the
        # documented one-pass staleness of the grow gates); the next
        # pass — well inside the 600 s cooldown — sees the freed
        # partition and the fractional gate waves the grow through.
        sched.trigger_resched("manual")
        clock.advance(5.0)
        reasons = [code
                   for r in sched.audit_records(0)
                   for d in r["deltas"] if d["job"] == a
                   for code in d["reasons"]]
        assert "hysteresis_bypassed_fractional_fit" in reasons, reasons
        assert sched.job_num_chips[a] == 3


class TestModelcheckFractional:
    def test_invariant_registered_and_documented(self):
        from vodascheduler_tpu.analysis import modelcheck
        assert "chip_oversubscribed" in modelcheck.INVARIANTS
        assert "overlapping-partition" in modelcheck.PLACEMENT_VARIANTS

    def test_overlapping_partition_tooth_caught_and_replayed(self):
        from vodascheduler_tpu.analysis import modelcheck
        result = modelcheck.explore(modelcheck.bounded_config(
            variant="overlapping-partition"))
        assert result.counterexample is not None
        assert "chip_oversubscribed" in result.counterexample["violation"]
        problems = modelcheck.replay_counterexample(result.counterexample)
        assert any("chip_oversubscribed" in p for p in problems)

    def test_bounded_profile_carries_fractional_job(self):
        from vodascheduler_tpu.analysis import modelcheck
        cfg = modelcheck.bounded_config()
        assert any(s.resource_class == "fractional" for s in cfg.jobs)
        # Round-trips through the counterexample config format.
        assert modelcheck.ModelConfig.from_dict(cfg.to_dict()) == cfg


class TestPerfBaselinePin:
    def test_committed_fractional_10k_decide_under_50ms(self):
        """The committed artifact pins the tentpole's perf acceptance:
        the 10k-job decide p95 stays under the PR 8 50 ms gate WITH
        fractional jobs in the vector (schema 6 `fractional` section,
        regenerated by `make perf-baseline`)."""
        with open(os.path.join(REPO, "doc", "perf_baseline.json")) as f:
            baseline = json.load(f)
        assert baseline["schema"] >= 6
        frac = {c["n_jobs"]: c for c in baseline["fractional"]}
        assert 10000 in frac
        assert 0 < frac[10000]["decide_wall_ms"]["p95"] < 50.0, \
            frac[10000]["decide_wall_ms"]
        assert 0 < frac[10000]["decide_wall_ms"]["mean"] < 50.0


class TestAdmissionValidation:
    def test_unknown_resource_class_rejected(self):
        clock = VirtualClock(start=1753760000.0)
        store, bus = JobStore(), EventBus()
        admission = AdmissionService(store, bus, clock)
        bad = JobSpec(name="typo", resource_class="fractionnal")
        results = admission.create_training_jobs(
            [bad, JobSpec(name="fine")])
        assert "unknown resource_class" in results[0]["error"]
        # All-or-nothing: the valid sibling is rejected with it and
        # zero residue lands in the store.
        assert "error" in results[1]
        assert store.list_jobs() == []
        ok = admission.create_training_jobs(
            [JobSpec(name="fine", resource_class="fractional")])
        assert "error" not in ok[0]


class TestModelcheckVariantGuard:
    def test_mismatched_profile_variant_fails_loudly(self):
        from vodascheduler_tpu.analysis import modelcheck
        with pytest.raises(ValueError, match="not a scheduler or "
                                             "placement variant"):
            modelcheck.explore(modelcheck.bounded_config(
                variant="route-book-start-mismatch"))
        with pytest.raises(ValueError, match="not an admission variant"):
            modelcheck.explore(modelcheck.fleet_config(
                variant="overlapping-partition"))


class TestFamilyTables:
    def test_interference_table_synced_with_trace_families(self):
        from vodascheduler_tpu.placement import comms
        comms.sanity_check_families()  # raises on drift
        assert all(0.0 <= f <= 0.5
                   for f in comms.FAMILY_INTERFERENCE.values())

    def test_interference_weights_bucketed(self):
        from vodascheduler_tpu.placement import comms
        assert comms.interference_weight_for_category("resnet50") > 0
        assert comms.interference_weight_for_category("unknown") == 0
        assert all(comms.interference_weight_for_category(c)
                   <= comms.MAX_INTERFERENCE_WEIGHT
                   for c in comms.FAMILY_INTERFERENCE)
