"""vodalint: the linter's rules, suppressions, baseline, and — most
importantly — the live tree. Each rule gets a positive (fires), a
negative (stays quiet), and a suppressed fixture; then the real package
must lint clean, and re-introducing a known-fixed violation (raw
time.time() in cluster/gke.py, an unknown reason code) must fail again —
the "deleting any one enforced invariant breaks the build" guarantee."""

import json
import os
import textwrap

from vodascheduler_tpu.analysis import vodalint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "vodascheduler_tpu")


def findings(src: str, rel: str):
    return vodalint.lint_source(textwrap.dedent(src), rel)


def rules_of(fs):
    return [f.rule for f in fs]


class TestClockDiscipline:
    def test_time_time_flagged_in_clocked_module(self):
        fs = findings("""
            import time
            def g():
                return time.time()
            """, "cluster/x.py")
        assert rules_of(fs) == ["clock-discipline"]

    def test_aliased_import_still_flagged(self):
        fs = findings("""
            import time as _walltime
            def g():
                _walltime.sleep(1)
            """, "scheduler/x.py")
        assert rules_of(fs) == ["clock-discipline"]

    def test_datetime_now_flagged(self):
        fs = findings("""
            import datetime
            def g():
                return datetime.datetime.now()
            """, "obs/x.py")
        assert rules_of(fs) == ["clock-discipline"]

    def test_monotonic_allowed(self):
        assert findings("""
            import time
            def g():
                return time.monotonic()
            """, "cluster/x.py") == []

    def test_unclocked_module_out_of_scope(self):
        assert findings("""
            import time
            def g():
                return time.time()
            """, "benchrunner/x.py") == []

    def test_suppression_with_reason(self):
        assert findings("""
            import time
            def g():
                time.sleep(1)  # vodalint: ignore[clock-discipline] modeled wall pause
            """, "cluster/x.py") == []

    def test_suppression_in_comment_block_above(self):
        assert findings("""
            import time
            def g():
                # vodalint: ignore[clock-discipline] models the real
                # blocking round trip; must not advance virtual time
                time.sleep(1)
            """, "cluster/x.py") == []

    def test_suppression_without_reason_is_a_finding(self):
        fs = findings("""
            import time
            def g():
                time.sleep(1)  # vodalint: ignore[clock-discipline]
            """, "cluster/x.py")
        assert rules_of(fs) == ["suppression-empty-reason"]

    def test_suppression_for_wrong_rule_does_not_apply(self):
        fs = findings("""
            import time
            def g():
                time.sleep(1)  # vodalint: ignore[thread-daemon] wrong rule
            """, "cluster/x.py")
        assert rules_of(fs) == ["clock-discipline"]


class TestLockDiscipline:
    def test_backend_mutator_under_lock_flagged(self):
        fs = findings("""
            class S:
                def bad(self):
                    with self._lock:
                        self.backend.start_job(spec, 4)
            """, "scheduler/x.py")
        assert rules_of(fs) == ["lock-discipline"]

    def test_emit_under_state_lock_flagged(self):
        fs = findings("""
            class B:
                def bad(self):
                    with self._state_lock:
                        self.emit(ev)
            """, "cluster/x.py")
        assert rules_of(fs) == ["lock-discipline"]

    def test_indirect_via_self_method_flagged(self):
        fs = findings("""
            class B:
                def bad(self):
                    with self._lock:
                        self._boom()
                def _boom(self):
                    self.emit(ev)
            """, "cluster/x.py")
        assert rules_of(fs) == ["lock-discipline"]
        assert "_boom" in fs[0].message

    def test_locked_or_deferred_target_checked(self):
        fs = findings("""
            class S:
                def handler(self):
                    self._locked_or_deferred(self._mutator)
                def _mutator(self):
                    self.backend.stop_job("j")
                    return []
            """, "scheduler/x.py")
        assert rules_of(fs) == ["lock-discipline"]

    def test_emit_after_lock_release_clean(self):
        assert findings("""
            class B:
                def good(self):
                    with self._lock:
                        ev = make()
                    self.emit(ev)
            """, "cluster/x.py") == []

    def test_deferred_lambda_under_lock_clean(self):
        # A lambda DEFINED under the lock runs later, on a timer thread
        # — the fake backend's epoch timers do exactly this.
        assert findings("""
            class B:
                def good(self):
                    with self._state_lock:
                        self.clock.call_at(5.0, lambda: self.emit(ev))
            """, "cluster/x.py") == []

    def test_read_only_backend_call_allowed(self):
        assert findings("""
            class S:
                def good(self):
                    with self._lock:
                        hosts = self.backend.list_hosts()
            """, "scheduler/x.py") == []

    def test_emit_laundered_through_module_helper_flagged(self):
        """The historical blind spot: the self-call map never followed
        bare-name module helpers, so `with self._lock: _notify(...)`
        hid an emit from the rule entirely."""
        fs = findings("""
            def _notify(bus, name):
                bus.emit("job_events", name)

            class S:
                def bad(self, name):
                    with self._lock:
                        _notify(self.bus, name)
            """, "scheduler/x.py")
        assert rules_of(fs) == ["lock-discipline"]
        assert "_notify" in fs[0].message

    def test_two_hop_module_helper_chain_flagged(self):
        fs = findings("""
            def _notify(bus, name):
                bus.emit("x", name)

            def _hop(bus, name):
                _notify(bus, name)

            class S:
                def bad(self, name):
                    with self._lock:
                        _hop(self.bus, name)
            """, "scheduler/x.py")
        assert rules_of(fs) == ["lock-discipline"]

    def test_method_calling_dangerous_helper_flagged(self):
        # self-method hop INTO a module helper: two different edge
        # kinds composed.
        fs = findings("""
            def _notify(bus, name):
                bus.emit("x", name)

            class S:
                def _tell(self, name):
                    _notify(self.bus, name)
                def bad(self, name):
                    with self._lock:
                        self._tell(name)
            """, "scheduler/x.py")
        assert rules_of(fs) == ["lock-discipline"]

    def test_module_helper_with_foreign_lock_region_flagged(self):
        # Module functions guard with the OWNER's lock (no self at
        # module scope) — that region is checked too.
        fs = findings("""
            def _notify(bus, name):
                bus.emit("x", name)

            def apply(sched, name):
                with sched._lock:
                    _notify(sched.bus, name)
            """, "scheduler/x.py")
        assert rules_of(fs) == ["lock-discipline"]

    def test_clean_module_helper_not_flagged(self):
        assert findings("""
            def _fmt(name):
                return name.title()

            class S:
                def good(self, name):
                    with self._lock:
                        self._t[name] = _fmt(name)
            """, "scheduler/x.py") == []

    def test_helper_called_outside_lock_clean(self):
        assert findings("""
            def _notify(bus, name):
                bus.emit("x", name)

            class S:
                def good(self, name):
                    with self._lock:
                        ev = name
                    _notify(self.bus, ev)
            """, "scheduler/x.py") == []


class TestVocab:
    def test_unknown_reason_code_flagged(self):
        fs = findings("""
            class S:
                def g(self, j):
                    self._add_reason(j, "cosmic_ray_flip")
            """, "scheduler/x.py")
        assert rules_of(fs) == ["vocab"]

    def test_known_reason_code_clean(self):
        assert findings("""
            class S:
                def g(self, j):
                    self._add_reason(j, "scale_out")
            """, "scheduler/x.py") == []

    def test_conditional_reason_codes_both_checked(self):
        fs = findings("""
            class S:
                def g(self, j, fast):
                    self._add_reason(j, "resize_inplace" if fast
                                     else "cold_fusion")
            """, "scheduler/x.py")
        assert rules_of(fs) == ["vocab"]
        assert "cold_fusion" in fs[0].message

    def test_unknown_trigger_flagged(self):
        fs = findings("""
            def g(s):
                s.trigger_resched("vibes")
            """, "service/x.py")
        assert rules_of(fs) == ["vocab"]

    def test_unknown_span_name_flagged(self):
        fs = findings("""
            def g(t):
                with t.span("backend.teleport", component="backend"):
                    pass
            """, "cluster/x.py")
        assert rules_of(fs) == ["vocab"]

    def test_known_span_name_clean(self):
        assert findings("""
            def g(t):
                with t.span("backend.start", component="backend"):
                    pass
            """, "cluster/x.py") == []

    def test_unknown_phase_name_flagged(self):
        fs = findings("""
            def g(prof):
                with prof.phase("vibes_stage"):
                    pass
            """, "scheduler/x.py")
        assert rules_of(fs) == ["vocab"]
        assert "PHASE_NAMES" in fs[0].message

    def test_known_phase_name_clean(self):
        assert findings("""
            def g(prof):
                with prof.phase("hungarian"):
                    pass
            """, "placement/x.py") == []

    def test_dead_vocabulary_entry_flagged(self, tmp_path):
        # A one-sided vocab edit: entry exists in obs/audit.py but no
        # code ever emits it. lint_package's reverse sweep catches it.
        pkg = tmp_path / "pkg"
        (pkg / "obs").mkdir(parents=True)
        (pkg / "obs" / "audit.py").write_text("# vocab lives here\n")
        (pkg / "scheduler").mkdir()
        (pkg / "scheduler" / "s.py").write_text(
            'class S:\n    def g(self, j):\n'
            '        self._add_reason(j, "started")\n')
        fs = vodalint.lint_package(str(pkg))
        dead = [f for f in fs if "used nowhere" in f.message]
        assert dead and all(f.path == "obs/audit.py" for f in dead)
        # "started" IS used by the fixture tree, so it is not dead.
        assert not any("'started'" in f.message for f in dead)


class TestMetricsLock:
    SRC = """
        import threading
        class C:
            def __init__(self):
                self._values = {}
                self._lock = threading.Lock()
            def unlocked(self):
                return self._values.get(1)
            def locked(self):
                with self._lock:
                    return self._values.get(1)
        """

    def test_unlocked_access_flagged_in_metrics_module(self):
        fs = findings(self.SRC, "common/metrics.py")
        assert rules_of(fs) == ["metrics-lock"]
        assert "unlocked" in fs[0].message

    def test_rule_scoped_to_metrics_module(self):
        assert findings(self.SRC, "common/other.py") == []

    def test_class_without_any_lock_flagged(self):
        """The canonical regression: a new instrument class that never
        creates the lock at all."""
        fs = findings("""
            class NewInstrument:
                def __init__(self):
                    self._values = {}
                def observe(self, v):
                    self._values[()] = v
            """, "common/metrics.py")
        assert rules_of(fs) == ["metrics-lock"]
        assert "no self._lock" in fs[0].message

    def test_lockless_class_without_state_clean(self):
        assert findings("""
            class Helper:
                def fmt(self, v):
                    return str(v)
            """, "common/metrics.py") == []


class TestThreadHygiene:
    def test_thread_without_daemon_flagged(self):
        fs = findings("""
            import threading
            def g():
                t = threading.Thread(target=g, name="voda-x")
                t.start()
            """, "service/x.py")
        assert rules_of(fs) == ["thread-daemon"]

    def test_daemon_kwarg_clean(self):
        assert findings("""
            import threading
            def g():
                threading.Thread(target=g, daemon=True,
                                 name="voda-x").start()
            """, "service/x.py") == []

    def test_daemon_attribute_after_construction_clean(self):
        assert findings("""
            import threading
            def g():
                timer = threading.Timer(1.0, g)
                timer.daemon = True
                timer.name = "voda-timer-x"
                timer.start()
            """, "common/x.py") == []

    def test_anonymous_thread_flagged(self):
        fs = findings("""
            import threading
            def g():
                threading.Thread(target=g, daemon=True).start()
            """, "service/x.py")
        assert rules_of(fs) == ["thread-name"]

    def test_non_voda_name_flagged(self):
        fs = findings("""
            import threading
            def g():
                threading.Thread(target=g, daemon=True,
                                 name="worker-1").start()
            """, "service/x.py")
        assert rules_of(fs) == ["thread-name"]

    def test_voda_fstring_name_clean(self):
        assert findings("""
            import threading
            def g(port):
                threading.Thread(target=g, daemon=True,
                                 name=f"voda-rest-{port}").start()
            """, "service/x.py") == []

    def test_name_attribute_after_construction_clean(self):
        assert findings("""
            import threading
            def g():
                t = threading.Thread(target=g, daemon=True)
                t.name = "voda-monitor-x"
                t.start()
            """, "cluster/x.py") == []

    def test_dynamic_name_expression_accepted(self):
        # A name the AST cannot read is not judged (the runtime witness
        # still sees the real name).
        assert findings("""
            import threading
            def g(name):
                threading.Thread(target=g, daemon=True,
                                 name=name).start()
            """, "service/x.py") == []

    def test_executor_without_prefix_flagged(self):
        fs = findings("""
            from concurrent.futures import ThreadPoolExecutor
            def g():
                return ThreadPoolExecutor(max_workers=2)
            """, "scheduler/x.py")
        assert rules_of(fs) == ["thread-name"]

    def test_executor_with_voda_prefix_clean(self):
        assert findings("""
            from concurrent.futures import ThreadPoolExecutor
            def g():
                return ThreadPoolExecutor(
                    max_workers=2, thread_name_prefix="voda-actuate")
            """, "scheduler/x.py") == []

    def test_executor_with_foreign_prefix_flagged(self):
        fs = findings("""
            from concurrent.futures import ThreadPoolExecutor
            def g():
                return ThreadPoolExecutor(
                    max_workers=2, thread_name_prefix="pool")
            """, "scheduler/x.py")
        assert rules_of(fs) == ["thread-name"]

    def test_thread_name_suppressable(self):
        assert findings("""
            import threading
            def g():
                threading.Thread(target=g, daemon=True).start()  # vodalint: ignore[thread-name] test-local helper thread
            """, "service/x.py") == []

    def test_stripping_a_thread_name_in_events_fails(self):
        """Re-introduction: the event-drain thread's role name is what
        lets vodarace attribute its accesses — deleting it must fail."""
        with open(os.path.join(PKG, "common", "events.py")) as f:
            src = f.read()
        needle = 'name=f"voda-event-drain-{topic}",\n'
        assert needle in src
        broken = src.replace(needle, "")
        fs = vodalint.lint_source(broken, "common/events.py")
        assert "thread-name" in {f.rule for f in fs}

    def test_submit_without_context_flagged(self):
        fs = findings("""
            def g(pool, fn):
                return pool.submit(fn)
            """, "scheduler/x.py")
        assert rules_of(fs) == ["executor-context"]

    def test_submit_with_context_propagation_clean(self):
        assert findings("""
            def g(pool, fn, parent, tracer):
                def run():
                    with use_context(parent, tracer):
                        fn()
                return pool.submit(run)
            """, "scheduler/x.py") == []


class TestJournalSeam:
    """The durability plane's seam rule + closed vocabularies
    (doc/durability.md)."""

    def test_transition_without_journal_flagged(self):
        fs = findings("""
            def f(self, job):
                lifecycle.transition(job, X, reason="accepted", chips=0)
            """, "scheduler/x.py")
        assert rules_of(fs) == ["journal-seam"]

    def test_ledger_without_journal_flagged(self):
        fs = findings("""
            def f(self):
                self.job_num_chips = BookingLedger()
            """, "durability/x.py")
        assert rules_of(fs) == ["journal-seam"]

    def test_seamed_calls_clean(self):
        fs = findings("""
            def f(self, job):
                lifecycle.transition(job, X, reason="accepted", chips=0,
                                     journal=self.journal)
                self.job_num_chips = BookingLedger(journal=None)
            """, "scheduler/x.py")
        assert fs == []

    def test_rule_scoped_to_seam_prefixes(self):
        fs = findings("""
            def f(self, job):
                lifecycle.transition(job, X, reason="accepted", chips=0)
            """, "analysis/x.py")
        assert fs == []

    def test_unknown_journal_kind_flagged(self):
        fs = findings("""
            def f(self):
                self.journal.append("jbogus", {"x": 1})
            """, "scheduler/x.py")
        assert rules_of(fs) == ["vocab"]
        assert "JOURNAL_KINDS" in fs[0].message

    def test_plain_list_append_not_confused_for_journal(self):
        fs = findings("""
            def f(self):
                out.append("definitely not a kind")
                self.journal.append("jbook", {"op": "commit"})
            """, "scheduler/x.py")
        assert fs == []

    def test_unknown_recovery_reason_flagged(self):
        fs = findings("""
            def f(divs):
                _add_divergence(divs, "vibes_diverged", "j0")
            """, "durability/x.py")
        assert rules_of(fs) == ["vocab"]
        assert "RECOVERY_REASONS" in fs[0].message

    def test_known_recovery_reason_clean(self):
        fs = findings("""
            def f(divs):
                _add_divergence(divs, "backend_lost_job", "j0")
            """, "durability/x.py")
        assert fs == []

    def test_unjournaling_a_scheduler_transition_fails(self):
        """Re-introduction: stripping the journal= seam from a live
        scheduler transition call must fail the lint again."""
        with open(os.path.join(PKG, "scheduler", "scheduler.py")) as f:
            src = f.read()
        assert "journal=self.journal" in src
        broken = src.replace(
            'reason="accepted",\n                             chips=0, '
            'tracer=self.tracer,\n                             '
            'pool=self.pool_id, journal=self.journal',
            'reason="accepted",\n                             chips=0, '
            'tracer=self.tracer,\n                             '
            'pool=self.pool_id')
        assert broken != src
        fs = vodalint.lint_source(broken, "scheduler/scheduler.py")
        assert any(f.rule == "journal-seam" for f in fs)

    def test_dead_journal_kind_flagged(self, tmp_path):
        """Reverse sweep: a JOURNAL_KINDS entry used nowhere in the
        tree is dead vocabulary (the two-sided contract)."""
        pkg = tmp_path / "pkg"
        (pkg / "obs").mkdir(parents=True)
        (pkg / "obs" / "audit.py").write_text("# vocab module\n")
        (pkg / "x.py").write_text("KINDS = ()\n")
        fs = vodalint.lint_package(str(pkg))
        dead = [f for f in fs if f.rule == "vocab"
                and "JOURNAL_KINDS" in f.message]
        assert dead, "journal kinds absent from a tree must be flagged"


class TestLiveTree:
    def test_package_lints_clean(self):
        fs = vodalint.lint_package(PKG)
        assert fs == [], "\n".join(
            f"{f.path}:{f.line}: [{f.rule}] {f.message}" for f in fs)

    def test_reintroducing_wall_clock_in_gke_fails(self):
        """The exact drift this PR fixed: raw time.time() event stamps
        in cluster/gke.py. Undo the fix in memory — the linter must
        catch it again."""
        with open(os.path.join(PKG, "cluster", "gke.py")) as f:
            src = f.read()
        assert "timestamp=self.clock.now()" in src
        broken = src.replace("timestamp=self.clock.now()",
                             "timestamp=time.time()")
        fs = vodalint.lint_source(broken, "cluster/gke.py")
        assert {f.rule for f in fs} == {"clock-discipline"}
        assert len(fs) >= 6  # one per event-emission site

    def test_unknown_reason_code_in_scheduler_fails(self):
        with open(os.path.join(PKG, "scheduler", "scheduler.py")) as f:
            src = f.read()
        broken = src.replace('self._add_reason(job, "started")',
                             'self._add_reason(job, "vibes_based")')
        assert broken != src
        fs = vodalint.lint_source(broken, "scheduler/scheduler.py")
        assert any(f.rule == "vocab" and "vibes_based" in f.message
                   for f in fs)

    def test_stripping_a_suppression_reason_fails(self):
        """Every inline suppression in the tree must carry a reason;
        blanking one turns it into a finding."""
        with open(os.path.join(PKG, "cluster", "fake.py")) as f:
            src = f.read()
        assert "vodalint: ignore[clock-discipline]" in src
        broken = src.replace(
            "vodalint: ignore[clock-discipline] models the REAL blocking",
            "vodalint: ignore[clock-discipline]")
        fs = vodalint.lint_source(broken, "cluster/fake.py")
        assert any(f.rule == "suppression-empty-reason" for f in fs)


class TestBaselineAndCli:
    def test_baseline_round_trip(self, tmp_path):
        bad = tmp_path / "pkg" / "cluster"
        bad.mkdir(parents=True)
        (bad / "x.py").write_text(
            "import time\ndef g():\n    return time.time()\n")
        base = tmp_path / "baseline.jsonl"
        # 1) without a baseline: non-zero exit, jsonl findings parse
        import io
        out = io.StringIO()
        rc = vodalint.run([str(tmp_path / "pkg")], fmt="jsonl",
                          stream=out)
        assert rc == 1
        recs = [json.loads(line) for line in
                out.getvalue().strip().splitlines()]
        assert recs and recs[0]["rule"] == "clock-discipline"
        # 2) write the baseline, re-run against it: exit 0
        rc = vodalint.run([str(tmp_path / "pkg")],
                          write_baseline_path=str(base), stream=io.StringIO())
        assert rc == 0
        loaded = vodalint.load_baseline(str(base))
        assert len(loaded) == 1
        rc = vodalint.run([str(tmp_path / "pkg")], baseline=str(base),
                          stream=io.StringIO())
        assert rc == 0
        # 3) a NEW violation is not masked by the old baseline
        (bad / "y.py").write_text(
            "import time\ndef h():\n    time.sleep(2)\n")
        rc = vodalint.run([str(tmp_path / "pkg")], baseline=str(base),
                          stream=io.StringIO())
        assert rc == 1

    def test_baseline_is_a_multiset(self, tmp_path):
        """A second, IDENTICAL violation in an already-baselined file
        (same rule, same message — every time.time() in one file shares
        both) must not be masked by the first one's baseline entry."""
        bad = tmp_path / "pkg" / "cluster"
        bad.mkdir(parents=True)
        (bad / "x.py").write_text(
            "import time\ndef g():\n    return time.time()\n")
        base = tmp_path / "baseline.jsonl"
        import io
        assert vodalint.run([str(tmp_path / "pkg")],
                            write_baseline_path=str(base),
                            stream=io.StringIO()) == 0
        assert vodalint.run([str(tmp_path / "pkg")], baseline=str(base),
                            stream=io.StringIO()) == 0
        (bad / "x.py").write_text(
            "import time\ndef g():\n    return time.time()\n"
            "def h():\n    return time.time()\n")
        assert vodalint.run([str(tmp_path / "pkg")], baseline=str(base),
                            stream=io.StringIO()) == 1

    def test_linting_a_package_subdirectory_keeps_rule_scope(
            self, tmp_path, monkeypatch):
        """Rel paths anchor at the PACKAGE root even when only a
        subdirectory is linted — otherwise every path-scoped rule
        silently disables itself and a dirty subtree lints clean."""
        import shutil
        broken = tmp_path / "vodascheduler_tpu"
        shutil.copytree(PKG, broken)
        gke = broken / "cluster" / "gke.py"
        gke.write_text(gke.read_text().replace(
            "timestamp=self.clock.now()", "timestamp=time.time()"))
        monkeypatch.setattr(vodalint, "_package_dir", lambda: str(broken))
        # Lint ONLY the cluster/ subdirectory of the (broken) package:
        # the clock-discipline findings must still fire, with
        # package-rooted paths.
        fs = vodalint.lint_package(str(broken / "cluster"))
        hits = [f for f in fs if f.rule == "clock-discipline"]
        assert len(hits) >= 6
        assert all(f.path.startswith("cluster/") for f in hits)
        # And the partial sweep must NOT declare the vocabulary dead.
        assert not any("used nowhere" in f.message for f in fs)

    def test_parse_error_has_its_own_rule(self, tmp_path):
        fs = vodalint.lint_source("def broken(:\n", "cluster/x.py")
        assert rules_of(fs) == ["parse-error"]

    def test_committed_baseline_matches_tree(self):
        """`make lint` contract: current findings minus the committed
        baseline must be empty (the tree itself is clean, so the
        committed baseline is empty too — every exception is inline)."""
        base_path = os.path.join(REPO, "vodalint_baseline.jsonl")
        assert os.path.exists(base_path)
        remaining = vodalint.subtract_baseline(
            vodalint.lint_package(PKG), vodalint.load_baseline(base_path))
        assert remaining == []

    def test_rule_registry_has_descriptions(self):
        for rule, doc in vodalint.RULES.items():
            assert doc and len(doc) > 20, rule


class TestStatusStore:
    """Satellite of the lifecycle PR: direct `job.status` stores outside
    common/lifecycle.py are findings (the tentpole refactor removed all
    of them, so the rule ships with a zero-entry baseline)."""

    def test_job_status_store_flagged_anywhere_in_package(self):
        fs = findings("""
            from vodascheduler_tpu.common.types import JobStatus
            def f(job):
                job.status = JobStatus.WAITING
            """, "benchrunner/x.py")
        assert rules_of(fs) == ["status-store"]

    def test_laundered_store_flagged_in_strict_modules(self):
        # No JobStatus literal in sight — but scheduler/service/replay
        # are strict: any non-self .status store is a lifecycle bypass.
        fs = findings("""
            def f(job, status):
                job.status = status
            """, "scheduler/x.py")
        assert rules_of(fs) == ["status-store"]

    def test_laundered_store_out_of_scope_elsewhere(self):
        assert findings("""
            def f(job, status):
                job.status = status
            """, "benchrunner/x.py") == []

    def test_self_status_store_clean(self):
        # obs spans set self.status = "ok"/"error" — their own field,
        # not a job lifecycle store.
        assert findings("""
            class Span:
                def ok(self):
                    self.status = "ok"
            """, "obs/x.py") == []

    def test_lifecycle_module_is_the_one_blessed_store(self):
        assert findings("""
            from vodascheduler_tpu.common.types import JobStatus
            def transition(job, to):
                job.status = to
                job.status = JobStatus.WAITING
            """, "common/lifecycle.py") == []

    def test_reintroducing_raw_status_store_in_scheduler_fails(self):
        """The re-introduction guarantee: put one of the eight removed
        `job.status =` sites back and the build fails again."""
        with open(os.path.join(PKG, "scheduler", "scheduler.py")) as f:
            src = f.read()
        # The tentpole refactor held: stores are gone (comparisons
        # like `job.status == ...` remain and are fine).
        assert "job.status = JobStatus" not in src
        broken = src + (
            "\n\ndef _backslide(job):\n"
            "    job.status = JobStatus.WAITING\n")
        fs = vodalint.lint_source(broken, "scheduler/scheduler.py")
        assert any(f.rule == "status-store" for f in fs)

    def test_rule_ships_with_zero_entry_baseline(self):
        """The committed baseline must not accept ANY status-store
        finding — the refactor removed every site, and new ones must
        fail, not baseline away."""
        baseline = vodalint.load_baseline(
            os.path.join(REPO, "vodalint_baseline.jsonl"))
        assert not any(rule == "status-store"
                       for (_, rule, _) in baseline)


class TestStatusReasonVocab:
    """STATUS_REASONS joins the closed vocabularies: unknown codes fail
    at the call site, unused codes fail the reverse sweep."""

    def test_unknown_status_reason_flagged(self):
        fs = findings("""
            from vodascheduler_tpu.common import lifecycle
            def f(job, to):
                lifecycle.transition(job, to, reason="vibes")
            """, "scheduler/x.py")
        assert "vocab" in rules_of(fs)
        assert any("vibes" in f.message for f in fs)

    def test_known_status_reason_clean(self):
        fs = findings("""
            from vodascheduler_tpu.common import lifecycle
            def f(job, to):
                lifecycle.transition(job, to, reason="preempted")
            """, "scheduler/x.py")
        assert "vocab" not in rules_of(fs)

    def test_conditional_status_reasons_both_checked(self):
        fs = findings("""
            from vodascheduler_tpu.common import lifecycle
            def f(job, to, done):
                lifecycle.transition(
                    job, to, reason="completed" if done else "imploded")
            """, "scheduler/x.py")
        assert any(f.rule == "vocab" and "imploded" in f.message
                   for f in fs)

    def test_unused_status_reason_fails_reverse_sweep(self, tmp_path):
        """Declaration sites (audit.py's vocab, lifecycle.py's
        TRANSITIONS) do NOT count as usage — only call sites do."""
        pkg = tmp_path / "pkg"
        (pkg / "obs").mkdir(parents=True)
        (pkg / "obs" / "audit.py").write_text("# vocab lives here\n")
        (pkg / "common").mkdir()
        # lifecycle.py declares every reason — and must not satisfy
        # the sweep by itself.
        (pkg / "common" / "lifecycle.py").write_text(
            'TRANSITIONS = {"x": ("accepted", "scheduled", "preempted",'
            ' "backend_lost", "resume", "completed", "failed",'
            ' "user_delete")}\n')
        (pkg / "scheduler").mkdir()
        (pkg / "scheduler" / "s.py").write_text(
            'class S:\n    def g(self, job, lifecycle, to):\n'
            '        lifecycle.transition(job, to, reason="accepted")\n')
        fs = vodalint.lint_package(str(pkg))
        dead = [f.message for f in fs
                if "STATUS_REASONS" in f.message
                and "used nowhere" in f.message]
        # "accepted" is genuinely used; the rest are dead despite the
        # lifecycle.py declarations.
        assert dead and not any("'accepted'" in m for m in dead)
        assert any("'preempted'" in m for m in dead)

    def test_live_tree_uses_every_status_reason(self):
        fs = vodalint.lint_package(PKG)
        assert not any("STATUS_REASONS" in f.message for f in fs)
