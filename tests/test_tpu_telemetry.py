"""Live libtpu telemetry (VERDICT r4 item 4), in two verifiable halves:

1. NAME rot guard — every SDK metric name runtime/tpu_monitor.py reads
   must be in this libtpu build's list_supported_metrics(). Always
   asserted when a TPU backend is reachable.
2. LIVENESS — sampling TpuMonitor during real training steps must export
   nonzero duty-cycle/tensorcore gauges. This half needs the libtpu
   monitoring DATA plane, which is chip-local: over a remote-chip
   transport (the axon tunnel) every get_metric(...).data() returns []
   (measured r5 — even static hbm_capacity_total; device.memory_stats()
   is likewise None), so the child detects that and the test skips with
   the transport reason rather than failing on an environment limit.

The hermetic mock test (test_metricscollector.py) proves the wiring;
this proves the names, and — on a chip-local host — the values.

Runs in a subprocess with the ambient (non-cpu) platform because the
conftest pins in-process jax to the CPU mesh.
"""

import os
import subprocess
import sys

import pytest

from tests.test_e2e_scheduler import _tpu_reachable

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Exit code the child uses for "names verified, but this transport has
# no monitoring data plane" — the test maps it to a skip.
NO_DATA_PLANE_EXIT = 42

_CHILD = """
import sys

import jax
assert jax.default_backend() == "tpu", jax.default_backend()

from vodascheduler_tpu.common.metrics import Registry
from vodascheduler_tpu.models import get_model
from vodascheduler_tpu.runtime.tpu_monitor import _SDK_SERIES, TpuMonitor
from vodascheduler_tpu.runtime.train import TrainSession

from libtpu import sdk  # the image must ship the SDK; absence is a FAIL

supported = set(sdk.tpumonitoring.list_supported_metrics())
print("supported:", sorted(supported))
# Half 1, the rot guard: every name the monitor reads must resolve on
# THIS libtpu build.
missing = [name for name, _, _ in _SDK_SERIES if name not in supported]
assert not missing, f"tpu_monitor reads unsupported metrics: {missing}"
print("NAMES_VERIFIED", sorted(name for name, _, _ in _SDK_SERIES))

# Data-plane probe: hbm_capacity_total is static — a chip-local host
# reports it even when idle. Empty means the monitoring data plane is
# not attached (remote-chip transport); the liveness half cannot run.
if not sdk.tpumonitoring.get_metric("hbm_capacity_total").data():
    print("NO_DATA_PLANE: get_metric('hbm_capacity_total').data() == []")
    sys.exit(%d)

reg = Registry()
mon = TpuMonitor(reg)
# llama_350m keeps the MXU genuinely busy between samples, so the
# duty-cycle/tensorcore windows cannot legitimately read zero.
session = TrainSession(get_model("llama_350m"), 1,
                       devices=jax.devices()[:1], global_batch_size=8)
# Read the GAUGES collect_once populated (the scrape surface) — never
# re-sample the SDK for comparison; two live samples differ.
duty, tc = [], []
for _ in range(3):
    session.run_steps(8)
    mon.collect_once()
    sample = {name: mon.m_sdk[name].value(accelerator="0")
              for name in ("duty_cycle_pct", "tensorcore_util",
                           "hbm_capacity_usage")}
    duty.append(sample["duty_cycle_pct"])
    tc.append(sample["tensorcore_util"])
    print("gauge sample:", sample)

assert max(duty) > 0.0, f"duty_cycle_pct never nonzero: {duty}"
assert max(tc) > 0.0, f"tensorcore_util never nonzero: {tc}"
# Memory gauges export for the real device too.
assert mon.m_devices.value() >= 1.0
print("LIVE_TELEMETRY_OK max_duty", max(duty), "max_tc", max(tc))
""" % NO_DATA_PLANE_EXIT


@pytest.mark.tpu
@pytest.mark.slow
def test_live_libtpu_telemetry_nonzero():
    if not _tpu_reachable():
        pytest.skip("no reachable TPU accelerator")
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    r = subprocess.run([sys.executable, "-c", _CHILD], capture_output=True,
                       text=True, timeout=900, env=env, cwd=REPO)
    sys.stdout.write(r.stdout[-2000:])
    if r.returncode == NO_DATA_PLANE_EXIT:
        # Names verified (the child asserts them before this exit); only
        # the liveness half is unavailable here.
        assert "NAMES_VERIFIED" in r.stdout
        pytest.skip("libtpu monitoring data plane absent on this "
                    "transport (chip-local API; remote-chip tunnel) — "
                    "metric names verified, liveness needs a chip-local "
                    "host")
    assert r.returncode == 0, (r.stdout[-1000:], r.stderr[-1500:])
    assert "LIVE_TELEMETRY_OK" in r.stdout
