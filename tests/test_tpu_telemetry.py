"""Live libtpu telemetry (VERDICT r4 item 4): the SDK metric names in
runtime/tpu_monitor.py are verified against the actual libtpu build by
sampling TpuMonitor DURING real training steps on the chip and asserting
the duty-cycle / tensorcore gauges export nonzero values. The hermetic
mock test (test_metricscollector.py) proves the wiring; only this proves
the names.

Runs in a subprocess with the ambient (non-cpu) platform because the
conftest pins in-process jax to the CPU mesh.
"""

import os
import subprocess
import sys

import pytest

from tests.test_e2e_scheduler import _tpu_reachable

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = """
import jax
assert jax.default_backend() == "tpu", jax.default_backend()

from vodascheduler_tpu.common.metrics import Registry
from vodascheduler_tpu.models import get_model
from vodascheduler_tpu.runtime.tpu_monitor import TpuMonitor
from vodascheduler_tpu.runtime.train import TrainSession

try:
    from libtpu import sdk
    print("supported:", sorted(sdk.tpumonitoring.list_supported_metrics()))
except Exception as e:
    print("sdk probe failed:", e)

reg = Registry()
mon = TpuMonitor(reg)
# llama_350m keeps the MXU genuinely busy between samples, so the
# duty-cycle/tensorcore windows cannot legitimately read zero.
session = TrainSession(get_model("llama_350m"), 1,
                       devices=jax.devices()[:1], global_batch_size=8)
# Read the GAUGES collect_once populated (the scrape surface) — never
# re-sample the SDK for comparison; two live samples differ.
duty, tc = [], []
for _ in range(3):
    session.run_steps(8)
    mon.collect_once()
    sample = {name: mon.m_sdk[name].value(accelerator="0")
              for name in ("duty_cycle_pct", "tensorcore_util",
                           "hbm_capacity_usage")}
    duty.append(sample["duty_cycle_pct"])
    tc.append(sample["tensorcore_util"])
    print("gauge sample:", sample)

# Gauge.value returns 0.0 for an absent series, so nonzero here proves
# both halves at once: the SDK metric NAME resolves on this libtpu
# build, and the value is live during real training.
assert max(duty) > 0.0, f"duty_cycle_pct never nonzero: {duty}"
assert max(tc) > 0.0, f"tensorcore_util never nonzero: {tc}"
# Memory gauges export for the real device too.
assert mon.m_devices.value() >= 1.0
print("LIVE_TELEMETRY_OK max_duty", max(duty), "max_tc", max(tc))
"""


@pytest.mark.tpu
@pytest.mark.slow
def test_live_libtpu_telemetry_nonzero():
    if not _tpu_reachable():
        pytest.skip("no reachable TPU accelerator")
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    r = subprocess.run([sys.executable, "-c", _CHILD], capture_output=True,
                       text=True, timeout=900, env=env, cwd=REPO)
    sys.stdout.write(r.stdout[-2000:])
    assert r.returncode == 0, (r.stdout[-1000:], r.stderr[-1500:])
    assert "LIVE_TELEMETRY_OK" in r.stdout
