"""Shared test fixtures: job builders with controllable speedup curves,
plus the jax capability probes behind the version-gated skip markers."""

from __future__ import annotations

from typing import Dict, Optional

import jax

# --- jax capability probes -------------------------------------------------
# The container pins jax 0.4.37; two newer-API surfaces gate a known set of
# tests (the "10 pre-existing jax-version failures" of PRs 1-3). Probing
# the capability (not the version string) keeps the markers correct across
# both older and newer installs.

# jax.sharding.get_abstract_mesh (jax >= 0.5): parallel/sharding.py's
# reshard_state uses it to respect an ambient use_mesh context — ring
# attention and the train-setup mesh-planning paths go through it.
JAX_HAS_ABSTRACT_MESH = hasattr(jax.sharding, "get_abstract_mesh")

# pallas CompilerParams (renamed from TPUCompilerParams in jax >= 0.5):
# ops/flash_attention.py builds its kernels with the new name, which the
# flash-attention smoke tests and every hwbench point that steps a model
# (llama/mixtral attention layers) need.
try:
    from jax.experimental.pallas import tpu as _pltpu
    JAX_HAS_PALLAS_COMPILER_PARAMS = hasattr(_pltpu, "CompilerParams")
except Exception:  # pragma: no cover - pallas missing entirely
    JAX_HAS_PALLAS_COMPILER_PARAMS = False

NEEDS_ABSTRACT_MESH = (
    "jax.sharding.get_abstract_mesh missing (needs jax >= 0.5; "
    "container pins an older jax)")
NEEDS_PALLAS_COMPILER_PARAMS = (
    "pallas CompilerParams missing (pre-rename jax; needs jax >= 0.5)")

from vodascheduler_tpu.common.job import (
    JobConfig,
    JobInfo,
    JobMetrics,
    JobSpec,
    TrainingJob,
    base_job_info,
)
from vodascheduler_tpu.common.types import JobStatus


def make_job(
    name: str,
    submit_time: float = 0.0,
    min_chips: int = 1,
    max_chips: int = 4,
    num_chips: int = 0,
    epochs: int = 10,
    priority: int = 0,
    remaining: float = 0.0,
    speedup: Optional[Dict[int, float]] = None,
    first_start_time: Optional[float] = None,
    status: JobStatus = JobStatus.WAITING,
    pool: str = "default",
) -> TrainingJob:
    cfg = JobConfig(num_chips=num_chips or min_chips, min_num_chips=min_chips,
                    max_num_chips=max_chips, epochs=epochs)
    spec = JobSpec(name=name, pool=pool, config=cfg, priority=priority)
    job = TrainingJob.from_spec(spec, submit_time=submit_time)
    job.status = status
    info = base_job_info(name, job.category, pool)
    info.estimated_remaining_seconds = remaining
    if speedup is not None:
        info.speedup = dict(speedup)
    job.info = info
    if first_start_time is not None:
        job.metrics.first_start_time = first_start_time
    return job
