"""Shared test fixtures: job builders with controllable speedup curves."""

from __future__ import annotations

from typing import Dict, Optional

from vodascheduler_tpu.common.job import (
    JobConfig,
    JobInfo,
    JobMetrics,
    JobSpec,
    TrainingJob,
    base_job_info,
)
from vodascheduler_tpu.common.types import JobStatus


def make_job(
    name: str,
    submit_time: float = 0.0,
    min_chips: int = 1,
    max_chips: int = 4,
    num_chips: int = 0,
    epochs: int = 10,
    priority: int = 0,
    remaining: float = 0.0,
    speedup: Optional[Dict[int, float]] = None,
    first_start_time: Optional[float] = None,
    status: JobStatus = JobStatus.WAITING,
    pool: str = "default",
) -> TrainingJob:
    cfg = JobConfig(num_chips=num_chips or min_chips, min_num_chips=min_chips,
                    max_num_chips=max_chips, epochs=epochs)
    spec = JobSpec(name=name, pool=pool, config=cfg, priority=priority)
    job = TrainingJob.from_spec(spec, submit_time=submit_time)
    job.status = status
    info = base_job_info(name, job.category, pool)
    info.estimated_remaining_seconds = remaining
    if speedup is not None:
        info.speedup = dict(speedup)
    job.info = info
    if first_start_time is not None:
        job.metrics.first_start_time = first_start_time
    return job
