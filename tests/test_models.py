"""Model zoo + training-core tests on the virtual 8-device CPU mesh.

Kept deliberately small (tiny configs, few steps) — CPU compile time
dominates; the real-device path is exercised by bench/graft entry.
"""

import os
import jax
import jax.numpy as jnp
import pytest

from vodascheduler_tpu.models import get_model, MODEL_REGISTRY

# CPU-mesh GSPMD compiles dominate (~6 min for the matrix on one core):
# the whole module is `slow`; tests/test_smoke_fast.py keeps a one-model
# slice of this path in `make test`.
pytestmark = pytest.mark.slow
from vodascheduler_tpu.parallel.mesh import MeshPlan
from vodascheduler_tpu.runtime import TrainSession


class TestRegistry:
    def test_all_registered_names_resolve(self):
        for name in MODEL_REGISTRY:
            assert get_model(name).module is not None

    def test_aliases(self):
        assert get_model("llama8b").name == "llama3_8b"
        assert get_model("mixtral").name == "mixtral_8x7b"

    def test_unknown(self):
        with pytest.raises(ValueError):
            get_model("gpt17")

    def test_flagship_param_count_is_8b_scale(self):
        from vodascheduler_tpu.models.llama import LLAMA3_8B
        assert 7e9 < LLAMA3_8B.param_count < 9e9


class TestTraining:
    def test_llama_tiny_trains_dp(self):
        s = TrainSession(get_model("llama_tiny"), num_chips=8,
                         global_batch_size=8)
        first = s.run_steps(1)
        for _ in range(3):
            last = s.run_steps(5)
        assert s.step == 16
        assert last < first  # synthetic but learnable (memorizes RNG stream stats)

    def test_sharding_plans_agree_on_loss(self):
        # The same seed must produce the same loss under any sharding —
        # GSPMD correctness across dp/fsdp/tp.
        losses = {}
        for label, plan in [("dp", MeshPlan(dp=8)),
                            ("fsdp_tp", MeshPlan(fsdp=4, tp=2)),
                            ("mixed", MeshPlan(dp=2, fsdp=2, tp=2))]:
            s = TrainSession(get_model("llama_tiny"), num_chips=8,
                             global_batch_size=8, plan=plan, seed=7)
            losses[label] = s.run_steps(2)
        vals = list(losses.values())
        for v in vals[1:]:
            assert abs(v - vals[0]) < 5e-2, losses

    def test_params_actually_sharded_under_fsdp(self):
        s = TrainSession(get_model("llama_tiny"), num_chips=8,
                         global_batch_size=8, plan=MeshPlan(fsdp=4, tp=2))
        leaves = jax.tree.leaves(s.state["params"])
        sharded = [x for x in leaves if not x.sharding.is_fully_replicated]
        assert len(sharded) >= len(leaves) // 2

    def test_moe_trains_with_ep(self):
        s = TrainSession(get_model("mixtral_tiny"), num_chips=8,
                         global_batch_size=8, plan=MeshPlan(dp=2, ep=4))
        loss = s.run_steps(2)
        assert 0 < loss < 20

    def test_ring_attention_training_path(self):
        s = TrainSession(get_model("llama_tiny"), num_chips=8,
                         global_batch_size=8, plan=MeshPlan(dp=2, sp=4))
        loss = s.run_steps(2)
        assert 0 < loss < 20


class TestGraftEntry:
    def test_entry_compiles(self):
        import __graft_entry__ as g

        fn, args = g.entry()
        out = jax.jit(fn)(*args)
        assert out.shape == (2, 64, 256)

    def test_dryrun_multichip(self, capsys, monkeypatch):
        import __graft_entry__ as g

        # The composed 16-device plans re-exec a subprocess; they have
        # their own test below — keep this one in-process.
        monkeypatch.setenv("VODA_DRYRUN_COMPOSED", "0")
        g.dryrun_multichip(8)
        out = capsys.readouterr().out
        assert "OK" in out

    def test_dryrun_composed_16(self):
        # The composed plans (dp.fsdp.tp.pp, dp.sp.ep, llama_1b
        # fsdp.tp) need 16 devices — the conftest pins 8, so this runs
        # through the same re-exec path the driver's 8-device dry run
        # takes (__graft_entry__._spawn_composed_16).
        import __graft_entry__ as g
        import contextlib
        import io

        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            g._spawn_composed_16()
        out = buf.getvalue()
        assert out.count("OK") == 3, out
        assert "llama_1b fsdp.tp" in out, out


class TestNmt:
    def test_nmt_tiny_trains_sharded(self):
        """Seq2seq (encoder-decoder + cross-attention) trains under a
        dp x tp mesh — the reference's Transformer-NMT family
        (neural_machine_translation_with_transformer.py), TPU-native."""
        s = TrainSession(get_model("nmt_tiny"), num_chips=8,
                         global_batch_size=8, plan=MeshPlan(dp=4, tp=2))
        first = s.run_steps(1)
        last = s.run_steps(10)
        assert s.step == 11
        assert last < first

    def test_nmt_resharding_resume(self, tmp_path):
        d = str(tmp_path / "ckpt")
        s = TrainSession(get_model("nmt_tiny"), num_chips=8,
                         global_batch_size=8, plan=MeshPlan(dp=8))
        s.run_steps(2)
        s.save(d)
        r = TrainSession.resume(get_model("nmt_tiny"), 4, d,
                                global_batch_size=8,
                                plan=MeshPlan(dp=2, tp=2))
        assert r.step == 2
        r.run_steps(1)
        assert r.step == 3


class TestRoutedMoE:
    """Routed (GShard one-hot-matmul) dispatch vs the dense oracle
    (VERDICT r2 item 7: capacity-bounded routing over ep behind the same
    MoEBlock interface)."""

    def _block_out(self, dispatch: str, capacity_factor: float = 100.0):
        import dataclasses

        import jax
        import jax.numpy as jnp

        from vodascheduler_tpu.models import mixtral

        cfg = dataclasses.replace(mixtral.MIXTRAL_TINY, dispatch=dispatch,
                                  capacity_factor=capacity_factor)
        block = mixtral.MoEBlock(cfg)
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, cfg.dim),
                              dtype=jnp.bfloat16)
        params = block.init(jax.random.PRNGKey(0), x)
        return block.apply(params, x)

    def test_routed_matches_dense_at_full_capacity(self):
        import jax.numpy as jnp
        dense = self._block_out("dense")
        routed = self._block_out("routed", capacity_factor=100.0)
        err = jnp.max(jnp.abs(dense.astype(jnp.float32)
                              - routed.astype(jnp.float32)))
        assert float(err) < 0.05, float(err)

    def test_capacity_drops_tokens_not_crashes(self):
        # Tight capacity: output differs from dense but stays finite.
        import jax.numpy as jnp
        routed = self._block_out("routed", capacity_factor=0.5)
        assert bool(jnp.all(jnp.isfinite(routed.astype(jnp.float32))))

    def test_gather_matches_routed_einsum(self):
        """gathered_ffn (scatter/gather, the single-chip dispatch) must
        reproduce the einsum formulation exactly — same routing, same
        drops — at both generous and tight capacity."""
        import jax.numpy as jnp
        for cap in (100.0, 0.5):
            routed = self._block_out("routed", capacity_factor=cap)
            gather = self._block_out("gather", capacity_factor=cap)
            err = jnp.max(jnp.abs(routed.astype(jnp.float32)
                                  - gather.astype(jnp.float32)))
            assert float(err) < 1e-2, (cap, float(err))

    def test_tied_router_routes_exactly_top_k(self):
        """Router ties (identical logits) must not diverge the two
        dispatch formulations: top_k_gating keeps EXACTLY top_k experts
        (index tie-break), so routed and gather agree even then."""
        import jax.numpy as jnp

        from vodascheduler_tpu.ops.moe_dispatch import top_k_gating
        probs = jnp.full((3, 4), 0.25)  # all four experts tied
        gate = top_k_gating(probs, 2)
        assert int((gate > 0).sum(-1).max()) == 2
        assert jnp.allclose(gate.sum(-1), 1.0)

    def test_unknown_dispatch_raises(self):
        import dataclasses

        import jax
        import jax.numpy as jnp
        import pytest as _pytest

        from vodascheduler_tpu.models import mixtral
        cfg = dataclasses.replace(mixtral.MIXTRAL_TINY, dispatch="gathered")
        block = mixtral.MoEBlock(cfg)
        x = jnp.zeros((1, 8, cfg.dim), jnp.bfloat16)
        with _pytest.raises(ValueError, match="unknown MixtralConfig"):
            block.init(jax.random.PRNGKey(0), x)

    def test_gather_trains(self):
        import dataclasses

        from vodascheduler_tpu.models import mixtral
        bundle = get_model("mixtral_tiny")
        bundle.module = mixtral.Mixtral(dataclasses.replace(
            mixtral.MIXTRAL_TINY, dispatch="gather"))
        s = TrainSession(bundle, num_chips=4, global_batch_size=4,
                         plan=MeshPlan(dp=4))
        loss = s.run_steps(2)
        assert 0 < loss < 20

    def test_routed_trains_with_ep(self):
        # The default mixtral_tiny bundle now routes; 2 steps on a
        # dp x ep mesh exercise dispatch/combine under ep sharding.
        s = TrainSession(get_model("mixtral_tiny"), num_chips=8,
                         global_batch_size=8, plan=MeshPlan(dp=2, ep=4))
        loss = s.run_steps(2)
        assert 0 < loss < 20

    def test_capacity_is_static_and_lane_rounded(self):
        from vodascheduler_tpu.ops.moe_dispatch import expert_capacity
        assert expert_capacity(1024, 8, 2, 1.25) == 320
        assert expert_capacity(32, 4, 2, 1.0) == 16
        assert expert_capacity(8, 8, 2, 1.0) == 8  # capped at T


class TestScannedLayers:
    """nn.scan-over-layers + per-layer remat (LlamaConfig.scan_layers):
    the big-model compile-time/memory shape. Param trees gain a leading
    layer axis under layers_scan/; sharding rules shift right by one."""

    def test_scanned_tiny_trains_sharded(self):
        from vodascheduler_tpu.models import llama
        from vodascheduler_tpu.models.registry import get_model
        bundle = get_model("llama_tiny")
        bundle.module = llama.Llama(llama.LLAMA_TINY_SCAN)
        s = TrainSession(bundle, num_chips=8, global_batch_size=8,
                         plan=MeshPlan(dp=2, fsdp=2, tp=2))
        l0 = s.run_steps(1)
        l1 = s.run_steps(10)  # enough steps to beat batch noise
        assert l1 < l0
        assert s.step == 11

    def test_scanned_params_shard_past_layer_axis(self):
        from vodascheduler_tpu.models import llama
        from vodascheduler_tpu.models.registry import get_model
        bundle = get_model("llama_tiny")
        bundle.module = llama.Llama(llama.LLAMA_TINY_SCAN)
        s = TrainSession(bundle, num_chips=8, global_batch_size=8,
                         plan=MeshPlan(fsdp=4, tp=2))
        q = s.state["params"]["layers_scan"]["block"]["attn"]["q_proj"]["kernel"]
        spec = q.sharding.spec
        # Leading layer axis unsharded; fsdp/tp land on the weight axes.
        assert spec[0] is None
        assert "fsdp" in str(spec) and "tp" in str(spec)

    def test_flagship_configs_scan(self):
        from vodascheduler_tpu.models import llama
        assert llama.LLAMA3_8B.scan_layers
        assert llama.LLAMA_350M.scan_layers
        assert not llama.LLAMA_TINY.scan_layers

    def test_remat_policy_numerics_match_full_remat(self):
        """Selective remat (REMAT_POLICIES) changes what's saved, not
        what's computed: the training trajectory must match full remat."""
        import dataclasses

        from vodascheduler_tpu.models import llama
        from vodascheduler_tpu.models.registry import get_model

        losses = {}
        for policy in (None, "dots_attn"):
            cfg = dataclasses.replace(llama.LLAMA_TINY_SCAN,
                                      remat_layers=True, remat_policy=policy)
            bundle = get_model("llama_tiny")
            bundle.module = llama.Llama(cfg)
            s = TrainSession(bundle, num_chips=4, global_batch_size=4,
                             plan=MeshPlan(dp=2, tp=2), seed=7)
            losses[policy] = s.run_steps(3)
        assert losses["dots_attn"] == pytest.approx(losses[None], rel=1e-4)

    def test_remat_policy_unknown_name_raises(self):
        from vodascheduler_tpu.models.layers import _resolve_remat_policy
        with pytest.raises(ValueError, match="unknown remat_policy"):
            _resolve_remat_policy("bogus")

    def test_scanned_mixtral_trains_with_ep(self):
        import dataclasses

        from vodascheduler_tpu.models import mixtral
        from vodascheduler_tpu.models.registry import get_model
        bundle = get_model("mixtral_tiny")
        cfg = dataclasses.replace(mixtral.MIXTRAL_TINY, scan_layers=True)
        bundle.module = mixtral.Mixtral(cfg)
        s = TrainSession(bundle, num_chips=8, global_batch_size=8,
                         plan=MeshPlan(dp=2, ep=4))
        loss = s.run_steps(2)
        assert 0 < loss < 20
        experts = s.state["params"]["layers_scan"]["block"]["moe"][
            "experts_gate_kernel"]
        spec = experts.sharding.spec
        assert spec[0] is None and "ep" in str(spec)


class TestPipelineParallel:
    """SPMD pipeline (parallel/pipeline.py): GPipe microbatch rotation
    over the scanned layer stack, pp axis on the stacked layer dim."""

    def test_pipeline_matches_sequential(self):
        # Same params, same batch: the pipelined dataflow must compute
        # exactly the sequential scanned forward (single device — the
        # schedule itself is device-count-independent).
        from vodascheduler_tpu.models import llama
        m = llama.Llama(llama.LLAMA_TINY_SCAN)
        rng = jax.random.PRNGKey(0)
        toks = jax.random.randint(rng, (4, 32), 0, 256)
        tgts = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 256)
        vs = m.init(rng, toks)
        seq = m.apply(vs, toks, targets=tgts)
        fwd = llama.pipeline_loss_fn(llama.LLAMA_TINY_SCAN,
                                     num_stages=2, num_microbatches=2)
        pp = fwd(vs["params"], toks, targets=tgts)
        assert abs(float(seq) - float(pp)) < 2e-2, (float(seq), float(pp))

    def test_pipeline_trains_on_pp_mesh(self):
        from vodascheduler_tpu.models import llama
        from vodascheduler_tpu.models.registry import get_model
        bundle = get_model("llama_tiny")
        bundle.module = llama.Llama(llama.LLAMA_TINY_SCAN)
        s = TrainSession(bundle, num_chips=8, global_batch_size=8,
                         plan=MeshPlan(dp=2, pp=2, tp=2))
        l0 = s.run_steps(1)
        l1 = s.run_steps(10)
        assert l1 < l0
        # The stacked layer axis is actually sharded over pp.
        q = s.state["params"]["layers_scan"]["block"]["attn"]["q_proj"]["kernel"]
        assert "pp" in str(q.sharding.spec)

    def test_pp_requires_scanned_llama(self):
        import pytest as _pytest
        with _pytest.raises(ValueError, match="scan_layers"):
            TrainSession(get_model("llama_tiny"), num_chips=8,
                         global_batch_size=8, plan=MeshPlan(dp=4, pp=2))

    def test_mixtral_pipeline_matches_sequential(self):
        import dataclasses

        from vodascheduler_tpu.models import mixtral
        cfg = dataclasses.replace(mixtral.MIXTRAL_TINY, scan_layers=True)
        m = mixtral.Mixtral(cfg)
        rng = jax.random.PRNGKey(0)
        toks = jax.random.randint(rng, (4, 32), 0, 256)
        tgts = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 256)
        vs = m.init(rng, toks)
        seq = m.apply(vs, toks, targets=tgts)
        fwd = mixtral.pipeline_loss_fn(cfg, num_stages=2, num_microbatches=2)
        pp = fwd(vs["params"], toks, targets=tgts)
        assert abs(float(seq) - float(pp)) < 2e-2, (float(seq), float(pp))

    def test_mixtral_trains_on_pp_ep_mesh(self):
        import dataclasses

        from vodascheduler_tpu.models import mixtral
        from vodascheduler_tpu.models.registry import get_model
        bundle = get_model("mixtral_tiny")
        cfg = dataclasses.replace(mixtral.MIXTRAL_TINY, scan_layers=True)
        bundle.module = mixtral.Mixtral(cfg)
        s = TrainSession(bundle, num_chips=8, global_batch_size=8,
                         plan=MeshPlan(dp=2, pp=2, ep=2))
        loss = s.run_steps(2)
        assert 0 < loss < 20


class TestLlama1B:
    """llama_1b (BASELINE configs 4-5 direction): the ≥1B-param
    single-chip point. These hermetic checks catch config rot before a
    chip session spends its slot on the bench (VERDICT r4 weak #2)."""

    def test_param_count_is_1b(self):
        from vodascheduler_tpu.models.llama import LLAMA_1B
        # The formula and the traced init must agree exactly — a drifted
        # formula would mislead plan_mesh and the MFU denominators.
        assert LLAMA_1B.param_count == 1_003_554_816
        m = get_model("llama_1b").module
        shapes = jax.eval_shape(
            m.init, jax.random.PRNGKey(0),
            jnp.zeros((1, 8), dtype=jnp.int32))
        traced = sum(l.size for l in jax.tree.leaves(shapes))
        assert traced == LLAMA_1B.param_count

    @staticmethod
    def _tiny_adafactor_bundle():
        # The llama_1b bundle with the model swapped to tiny shapes:
        # same adafactor optimizer branch the 1B bench will hit.
        import dataclasses

        from vodascheduler_tpu.models import llama
        from vodascheduler_tpu.models.registry import _lm_batch
        bundle = get_model("llama_1b")
        assert bundle.optimizer == "adafactor"
        return dataclasses.replace(
            bundle, module=llama.Llama(llama.LLAMA_TINY_SCAN),
            make_batch=_lm_batch(llama.LLAMA_TINY_SCAN.vocab_size, 64),
            params_b=0.0, seq_len=64)

    def test_adafactor_bundle_steps_tiny(self):
        s = TrainSession(self._tiny_adafactor_bundle(), num_chips=8,
                         global_batch_size=8)
        first = s.run_steps(1)
        last = s.run_steps(5)
        assert jnp.isfinite(first) and jnp.isfinite(last)
        assert last < first

    def test_adafactor_state_resharding_resume(self, tmp_path):
        # Adafactor's factored-moment state tree (optax FactoredState:
        # v_row/v_col for matrices, full v for vectors) must survive the
        # Orbax save -> restart-at-new-topology -> resharded restore that
        # the 1B bench's resize flow depends on — adamw trees have this
        # proof elsewhere, adafactor's shape-heterogeneous tree did not.
        tiny = self._tiny_adafactor_bundle()
        d = str(tmp_path / "ckpt")
        s = TrainSession(tiny, num_chips=8, global_batch_size=8,
                         plan=MeshPlan(dp=8))
        s.run_steps(2)
        s.save(d)
        r = TrainSession.resume(tiny, 4, d, global_batch_size=8,
                                plan=MeshPlan(dp=2, fsdp=2))
        assert r.step == 2
        import numpy as np
        before = [jax.device_get(l) for l in jax.tree.leaves(s.state["opt_state"])]
        after = [jax.device_get(l) for l in jax.tree.leaves(r.state["opt_state"])]
        assert len(before) == len(after)
        for b, a in zip(before, after):
            assert b.shape == a.shape
            assert np.allclose(b, a), "opt_state changed across restore"
        r.run_steps(1)
        assert r.step == 3

    def test_abstract_hbm_fit_on_one_v5e(self):
        """Shape-level proof the bench point fits: f32 params + adafactor
        state + the in-step transients (f32 grad tree, bf16 param cast,
        per-layer remat boundary activations) under 16 GB at the bench
        batch (bench.py HW_MODEL_POINTS: llama_1b at B=4)."""
        from vodascheduler_tpu.models.llama import LLAMA_1B
        from vodascheduler_tpu.runtime.train import make_train_setup

        bundle = get_model("llama_1b")
        setup = make_train_setup(bundle, 1, devices=jax.devices()[:1],
                                 global_batch_size=4)
        state_bytes = sum(l.size * l.dtype.itemsize
                          for l in jax.tree.leaves(setup.eval_shape_state))
        params = LLAMA_1B.param_count
        # Adafactor's factored moments must be ~order-of-magnitude under
        # Adam's 8 B/param — the reason this bundle exists.
        opt_bytes = state_bytes - 4 * params - 4
        assert opt_bytes < 1.0 * params, opt_bytes / params
        cfg = LLAMA_1B
        B = 4
        acts = cfg.num_layers * B * cfg.max_seq_len * cfg.dim * 2  # bf16
        est = state_bytes + 4 * params + 2 * params + acts
        # ~11.0 GB measured abstractly; 16 GB chip. The margin absorbs
        # XLA workspace/fragmentation the abstract sum can't see.
        assert est < 0.80 * 16e9, est / 1e9


class TestScaleFeasibility:
    def test_bench_hw_points_fit_hbm_abstract(self):
        """Every bench.py HW_MODEL_POINT must fit a 16 GB v5e at the
        shape level (state + f32 grads + bf16 cast + remat boundary
        activations < 80% of HBM) — a point added without this check
        wastes its chip-session slot on an OOM."""
        import sys

        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from bench import HW_MODEL_POINTS
        from vodascheduler_tpu.runtime.train import make_train_setup

        for name, batch in HW_MODEL_POINTS:
            bundle = get_model(name)
            setup = make_train_setup(bundle, 1, devices=jax.devices()[:1],
                                     global_batch_size=batch)
            leaves = jax.tree.leaves(setup.eval_shape_state)
            state = sum(l.size * l.dtype.itemsize for l in leaves)
            params = sum(l.size for l in
                         jax.tree.leaves(setup.eval_shape_state["params"]))
            cfg = bundle.module.cfg
            tokens = batch * cfg.max_seq_len
            acts = cfg.num_layers * tokens * cfg.dim * 2  # remat boundary
            cap = 0.80 * 16e9
            if getattr(cfg, "remat_policy", None) == "dots_attn":
                # The saved matmul outputs per layer (bf16): q, kv pair,
                # attention-kernel out, attn out-proj, gate+up, down —
                # the HBM this policy trades for its recompute savings
                # (the down-proj dot is a distinct buffer from the
                # post-residual boundary carry counted above).
                kv_dim = cfg.dim * cfg.num_kv_heads // cfg.num_heads
                per_layer = tokens * (4 * cfg.dim + 2 * kv_dim
                                      + 2 * cfg.mlp_hidden) * 2
                acts += cfg.num_layers * per_layer
                # This sum is an upper bound — XLA's live-range peak
                # never holds every saved dot at once the way it holds
                # full-remat boundaries — so the gate runs at 85%,
                # calibrated by the measured point: llama_350m_af B=8
                # estimates ~12.9 GB here and runs green on the chip
                # (526 ms, doc/benchmarks.md).
                cap = 0.85 * 16e9
            est = state + 4 * params + 2 * params + acts
            assert est < cap, (name, batch, est / 1e9)

    @pytest.mark.slow
    def test_llama3_8b_state_shards_within_v5p_hbm(self):
        """BASELINE config 4 (Llama-3-8B FSDP elastic on v5p-64), proven
        at the shape level: trace the full train state abstractly on a
        64-device mesh, apply the production sharding rules, and check
        the per-chip shard bytes (fp32 params + AdamW moments) fit a
        v5p chip's 95 GB HBM with generous activation headroom — a rule
        regression that silently replicates the 8B params fails this."""
        import subprocess
        import sys

        code = """
import jax; jax.config.update('jax_platforms', 'cpu')
from vodascheduler_tpu.models import get_model
from vodascheduler_tpu.runtime.train import make_train_setup

# The PRODUCTION path end to end: make_train_setup plans the mesh,
# traces the full train state (params + AdamW moments + extras) and
# derives the shardings exactly as a real v5p-64 job would.
bundle = get_model('llama3_8b')
setup = make_train_setup(bundle, 64, devices=jax.devices()[:64])
shapes, shardings = setup.eval_shape_state, setup.state_shardings

total = per_chip = 0
for leaf, sh in zip(jax.tree.leaves(shapes), jax.tree.leaves(
        shardings, is_leaf=lambda x: hasattr(x, 'shard_shape'))):
    nbytes = leaf.size * leaf.dtype.itemsize
    shard_n = 1
    for d in sh.shard_shape(leaf.shape):
        shard_n *= d
    total += nbytes
    per_chip += shard_n * leaf.dtype.itemsize
print('plan', {k: v for k, v in setup.plan.axis_sizes().items() if v > 1})
print('total_gb', round(total / 1e9, 2))
print('per_chip_gb', round(per_chip / 1e9, 2))
assert total > 80e9, total                # fp32 ~7.2B params x 12 bytes
assert per_chip < 0.5 * 95e9, per_chip    # half a v5p chip, rest for activations
assert per_chip < total / 16, (per_chip, total)  # genuinely sharded
print('OK')
"""
        # Preserve any existing XLA flags (same pattern as supervisor.py).
        flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
                 if not f.startswith("--xla_force_host_platform_device_count")]
        flags.append("--xla_force_host_platform_device_count=64")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   XLA_FLAGS=" ".join(flags))
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True, timeout=600,
                              env=env)
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "OK" in proc.stdout, proc.stdout


class TestLlama350mAf:
    """llama_350m_af: the measured memory-for-FLOPs flagship variant
    (Adafactor + dots_attn selective remat; doc/benchmarks.md "Remat
    policy sweep" r5 follow-up). Same arithmetic as llama_350m — only
    the optimizer state and the remat save-set differ."""

    def test_bundle_shape_and_knobs(self):
        from vodascheduler_tpu.models.llama import LLAMA_350M, LLAMA_350M_AF

        bundle = get_model("llama_350m_af")
        assert bundle.optimizer == "adafactor"
        assert bundle.module.cfg.remat_policy == "dots_attn"
        assert LLAMA_350M_AF.param_count == LLAMA_350M.param_count

    def test_8k_twin_knobs(self):
        from vodascheduler_tpu.models.llama import LLAMA_350M_8K_AF

        bundle = get_model("llama_350m_8k_af")
        assert bundle.optimizer == "adafactor"
        assert bundle.module.cfg.remat_policy == "dots_attn"
        assert bundle.module.cfg.max_seq_len == 8192
        assert bundle.seq_len == 8192
        assert LLAMA_350M_8K_AF.max_seq_len == 8192

    def test_tiny_twin_trains(self):
        """The exact knob combination (adafactor + dots_attn + scan)
        steps on tiny shapes — guards the policy name and the optimizer
        wiring without full-size compile cost."""
        import dataclasses

        from vodascheduler_tpu.models import llama
        from vodascheduler_tpu.models.registry import (
            TRANSFORMER_RULES, ModelBundle, _lm_batch, _lm_fused_loss)
        from vodascheduler_tpu.runtime.train import make_train_setup

        cfg = dataclasses.replace(llama.LLAMA_TINY_SCAN, remat_layers=True,
                                  remat_policy="dots_attn")
        bundle = ModelBundle(
            name="tiny_af", module=llama.Llama(cfg),
            make_batch=_lm_batch(cfg.vocab_size, 64),
            loss_fn=_lm_fused_loss, rules=TRANSFORMER_RULES, seq_len=64,
            optimizer="adafactor")
        setup = make_train_setup(bundle, 1, devices=jax.devices()[:1],
                                 global_batch_size=2)
        state = setup.init_fn(jax.random.PRNGKey(0))
        batch = setup.make_batch(2, jax.random.PRNGKey(1))
        state, loss = setup.train_step(state, batch)
        assert float(loss) > 0
