"""vodarace: the thread-role × shared-state race checker, its pinned
ownership map, and the runtime access witness. Each rule gets a
positive (fires on a synthetic tree), a negative (stays quiet), and a
suppressed fixture; then the live package must check clean, every
seeded selftest variant must be CAUGHT again when re-applied, the
committed doc/thread_roles.json must match a fresh inference, and the
RaceWitness must flag observations that escape the map — the "deleting
any one enforced invariant breaks the build" guarantee, extended to
the concurrency plane."""

import io
import json
import os
import textwrap
import threading

import pytest

from vodascheduler_tpu.analysis import RaceViolation, RaceWitness, vodarace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "vodascheduler_tpu")
THREAD_ROLES = os.path.join(REPO, "doc", "thread_roles.json")


def analyze(tmp_path, sources):
    """Analyze a synthetic tree: {rel: src} against an empty package
    root, so no live-tree class couples into the fixture call graph."""
    overrides = {rel: textwrap.dedent(src) for rel, src in sources.items()}
    return vodarace.analyze_package(str(tmp_path), overrides=overrides)


def findings(tmp_path, sources):
    return vodarace.race_findings(analyze(tmp_path, sources))


def rules_of(fs):
    return [f.rule for f in fs]


# A class whose table is touched by a REST-role handler thread and a
# role thread it starts itself; `tail` controls the racy method's body.
def _two_role_fixture(tail, init_extra=""):
    tail_block = textwrap.indent(
        textwrap.dedent(tail).strip("\n") or "pass", "        ")
    extra = textwrap.indent(textwrap.dedent(init_extra).strip("\n"), "    ")
    src = (
        "import threading\n"
        "\n"
        "\n"
        "class Sched:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.RLock()\n"
        "        self._table = {}\n"
        "\n"
        + (extra + "\n\n" if extra.strip() else "")
        + "    def start(self):\n"
        "        t = threading.Thread(target=self._loop,\n"
        "                             name=\"voda-monitor-x\",\n"
        "                             daemon=True)\n"
        "        t.start()\n"
        "\n"
        "    def _loop(self):\n"
        + tail_block + "\n")
    return {"scheduler/x.py": src,
            "service/rest.py": """
        def make_handlers(sched):
            def get_table(body, query):
                return dict(sched._table)
            return {"GET /table": get_table}
        """}


class TestUnguardedSharedWrite:
    def test_two_roles_unguarded_write_flagged(self, tmp_path):
        fs = findings(tmp_path, _two_role_fixture("self._table['k'] = 1"))
        assert rules_of(fs) == ["unguarded-shared-write"]
        assert fs[0].path == "scheduler/x.py"
        assert "Sched._table" in fs[0].message
        assert "collector" in fs[0].message and "rest" in fs[0].message

    def test_single_role_write_not_flagged(self, tmp_path):
        # Only the collector loop touches the table — private state of
        # one role is not a race, however unlocked.
        src = _two_role_fixture("self._table['k'] = 1")
        del src["service/rest.py"]
        assert findings(tmp_path, src) == []

    def test_mutator_call_counts_as_write(self, tmp_path):
        # `self._table.clear()` mutates the container: races exactly
        # like assignment even though the AST sees only a Load.
        fs = findings(tmp_path, _two_role_fixture("self._table.clear()"))
        assert rules_of(fs) == ["unguarded-shared-write"]

    def test_augassign_counts_as_write(self, tmp_path):
        fixture = _two_role_fixture("self._gen += 1")
        fixture["scheduler/x.py"] = fixture["scheduler/x.py"].replace(
            "self._table = {}", "self._table = {}\n        self._gen = 0")
        fixture["service/rest.py"] = fixture["service/rest.py"].replace(
            "sched._table", "sched._gen")
        fs = findings(tmp_path, fixture)
        assert rules_of(fs) == ["unguarded-shared-write"]
        assert "Sched._gen" in fs[0].message

    def test_suppressed_with_reason_clean(self, tmp_path):
        fs = findings(tmp_path, _two_role_fixture(
            "self._table['k'] = 1  "
            "# vodarace: ignore[unguarded-shared-write] GIL-atomic"))
        assert fs == []

    def test_suppression_without_reason_flagged(self, tmp_path):
        fs = findings(tmp_path, _two_role_fixture(
            "self._table['k'] = 1  "
            "# vodarace: ignore[unguarded-shared-write]"))
        assert "suppression-empty-reason" in rules_of(fs)


class TestGuardedReadUnguardedWrite:
    def test_guarded_elsewhere_unguarded_here_flagged(self, tmp_path):
        fs = findings(tmp_path, _two_role_fixture(
            "self._table['k'] = 1",
            init_extra="""
            def put(self, k, v):
                with self._lock:
                    self._table[k] = v
            """))
        assert rules_of(fs) == ["guarded-read-unguarded-write"]
        assert "guarded at" in fs[0].message
        # the finding pins the UNGUARDED write, not the locked one
        assert fs[0].line > 1

    def test_all_sites_locked_clean(self, tmp_path):
        assert findings(tmp_path, _two_role_fixture("""
            with self._lock:
                self._table['k'] = 1
            """, init_extra="""
            def put(self, k, v):
                with self._lock:
                    self._table[k] = v
            """)) == []

    def test_lock_via_helper_method_recognized(self, tmp_path):
        # The locked-context fixpoint: a helper only ever called with
        # the lock held inherits guarded-ness.
        assert findings(tmp_path, _two_role_fixture("""
            with self._lock:
                self._apply()
            """, init_extra="""
            def _apply(self):
                self._table['k'] = 1
            """)) == []


class TestImmutableAndScope:
    def test_immutable_after_init_exempt(self, tmp_path):
        # Written only in __init__, read everywhere: config, not state.
        fs = findings(tmp_path, _two_role_fixture(
            "x = self._table",
            init_extra=""))
        assert fs == []

    def test_parse_error_reported(self, tmp_path):
        fs = findings(tmp_path, {"scheduler/x.py": "def broken(:\n"})
        assert rules_of(fs) == ["parse-error"]
        assert fs[0].path == "scheduler/x.py"

    def test_analysis_tooling_creates_no_roles(self, tmp_path):
        # A driver under analysis/ calling into the class must not
        # create role edges (ANALYZE_EXCLUDE).
        src = _two_role_fixture("self._table['k'] = 1")
        del src["service/rest.py"]
        src["analysis/driver.py"] = """
            def drive(s):
                s._table["probe"] = 0
            """
        assert findings(tmp_path, src) == []


class TestRolePlumbing:
    def test_role_for_thread_name_prefixes(self):
        assert vodarace.role_for_thread_name("voda-rest-8080") == "rest"
        assert vodarace.role_for_thread_name(
            "voda-scheduler-daemon-pool0") == "decide"
        assert vodarace.role_for_thread_name("voda-actuate-0") == \
            "actuate-worker"
        assert vodarace.role_for_thread_name("voda-event-drain-jobs") == \
            "drainer"
        assert vodarace.role_for_thread_name("voda-standby-apply") == \
            "standby"

    def test_unknown_names_are_main(self):
        assert vodarace.role_for_thread_name("MainThread") == "main"
        assert vodarace.role_for_thread_name("Thread-7") == "main"
        assert vodarace.role_for_thread_name(None) == "main"

    def test_every_prefix_maps_to_a_known_role(self):
        assert set(vodarace.ROLE_PREFIXES.values()) <= set(vodarace.ROLES)

    def test_thread_entry_points_discovered(self, tmp_path):
        an = analyze(tmp_path, _two_role_fixture("pass"))
        assert any("scheduler/x.py:Sched._loop" in e
                   for e in an.entry_points.get("collector", ()))


class TestLiveTreeAndVariants:
    def test_live_tree_clean(self):
        fs = vodarace.race_findings(vodarace.analyze_package(PKG))
        assert fs == [], "\n".join(
            f"{f.path}:{f.line}: [{f.rule}] {f.message}" for f in fs)

    @pytest.mark.parametrize("name", sorted(vodarace.VARIANTS))
    def test_variant_reintroduction_caught(self, name):
        rel, transform, rules = vodarace.VARIANTS[name]
        with open(os.path.join(PKG, rel), encoding="utf-8") as f:
            src = f.read()
        fs = vodarace.race_findings(
            vodarace.analyze_package(PKG, overrides={rel: transform(src)}))
        hits = [f for f in fs if f.path == rel and f.rule in rules]
        assert hits, (f"seeded race {name} not caught; findings in "
                      f"{rel}: {[(f.line, f.rule) for f in fs]}")
        assert all(f.line > 0 for f in hits)

    def test_selftest_passes_and_reports_file_line(self):
        out = io.StringIO()
        assert vodarace.selftest(stream=out) == 0
        text = out.getvalue()
        assert "vodarace selftest: OK" in text
        for name in vodarace.VARIANTS:
            assert f"selftest {name}: CAUGHT" in text
        # every CAUGHT line carries a file:line anchor
        for line in text.splitlines():
            if ": CAUGHT" in line:
                assert ".py:" in line


class TestPinnedMap:
    def test_map_matches_committed_artifact(self):
        fresh = vodarace.build_map(vodarace.analyze_package(PKG))
        with open(THREAD_ROLES, encoding="utf-8") as f:
            pinned = json.load(f)
        assert fresh == pinned, (
            "doc/thread_roles.json is stale — regenerate with "
            "`make thread-roles` and review the ownership diff")

    def test_map_is_deterministic(self):
        a = vodarace.build_map(vodarace.analyze_package(PKG))
        b = vodarace.build_map(vodarace.analyze_package(PKG))
        assert json.dumps(a, sort_keys=True) == json.dumps(b,
                                                           sort_keys=True)

    def test_map_schema(self):
        with open(THREAD_ROLES, encoding="utf-8") as f:
            m = json.load(f)
        assert m["schema"] == vodarace.SCHEMA_VERSION
        assert m["role_prefixes"] == dict(sorted(
            vodarace.ROLE_PREFIXES.items()))
        assert "main" not in m["roles"]
        for role, body in m["roles"].items():
            assert role in vodarace.ROLES
            assert set(body) == {"entry_points", "access"}
            for cls, attrs in body["access"].items():
                for attr, kinds in attrs.items():
                    assert set(kinds) <= {"read", "write"}
                    assert set(kinds.values()) <= {
                        "guarded", "unguarded", "mixed"}

    def test_scheduler_core_ownership_pinned(self):
        # Load-bearing rows: the decide role owns the scheduler tables
        # under the lock; REST reads the snapshot cache.
        with open(THREAD_ROLES, encoding="utf-8") as f:
            m = json.load(f)
        decide = m["roles"]["decide"]["access"]["Scheduler"]
        assert "_in_resched" in decide
        assert any(kinds.get("write") == "guarded"
                   for kinds in decide.values())

    def test_map_fixture_roundtrip(self, tmp_path):
        an = analyze(tmp_path, _two_role_fixture(
            "self._table['k'] = 1",
            init_extra="""
            def put(self, k, v):
                with self._lock:
                    self._table[k] = v
            """))
        m = vodarace.build_map(an)
        rest = m["roles"]["rest"]["access"]["Sched"]
        assert rest["_table"]["read"] == "unguarded"
        coll = m["roles"]["collector"]["access"]["Sched"]
        assert coll["_table"]["write"] == "unguarded"
        path = tmp_path / "roles.json"
        vodarace.write_map(str(path), an)
        assert json.loads(path.read_text()) == m


class TestCLI:
    def test_run_clean_exits_zero(self):
        out = io.StringIO()
        assert vodarace.run([PKG], stream=out) == 0
        assert "vodarace: 0 finding(s)" in out.getvalue()

    def test_jsonl_byte_stable(self):
        a, b = io.StringIO(), io.StringIO()
        vodarace.run([PKG], fmt="jsonl", stream=a)
        vodarace.run([PKG], fmt="jsonl", stream=b)
        assert a.getvalue() == b.getvalue()

    def test_sarif_output_well_formed(self):
        out = io.StringIO()
        vodarace.run([PKG], fmt="sarif", stream=out)
        sarif = json.loads(out.getvalue())
        assert sarif["version"] == "2.1.0"
        tool = sarif["runs"][0]["tool"]["driver"]
        assert tool["name"] == "vodarace"
        assert {r["id"] for r in tool["rules"]} == set(vodarace.RULES)
        assert sarif["runs"][0]["results"] == []


# ---- the runtime access witness -------------------------------------------


class _Box:
    """A deliberately tiny shared object for witness unit tests."""

    def __init__(self):
        self._lock = threading.RLock()
        self._val = 0
        self._frozen = "cfg"


def _run_as(name, fn):
    err = []

    def wrapped():
        try:
            fn()
        except Exception as e:  # noqa: BLE001 - re-raised below
            err.append(e)

    t = threading.Thread(target=wrapped, name=name)
    t.start()
    t.join(timeout=10.0)
    assert not t.is_alive()
    if err:
        raise err[0]


def _pinned(access=None, immutable=None):
    return {"schema": 1, "role_prefixes": dict(vodarace.ROLE_PREFIXES),
            "roles": {"rest": {"entry_points": [],
                               "access": access or {}}},
            "immutable": immutable or {}}


class TestRaceWitness:
    def test_records_role_attributed_accesses(self):
        w = RaceWitness()
        box = _Box()
        w.watch(box, guard_locks=())
        _run_as("voda-rest-x", lambda: setattr(box, "_val", 1))
        assert ("rest", "_Box", "_val", "write", False) in w.observations()

    def test_main_thread_invisible(self):
        w = RaceWitness()
        box = _Box()
        w.watch(box)
        box._val = 2
        _ = box._val
        assert w.observations() == []

    def test_subset_violation_on_unmapped_access(self):
        w = RaceWitness()
        box = _Box()
        w.watch(box)
        _run_as("voda-rest-x", lambda: setattr(box, "_val", 1))
        problems = w.problems(_pinned())
        assert problems and "not in the pinned ownership map" in problems[0]
        with pytest.raises(RaceViolation):
            w.check(_pinned())

    def test_mapped_access_accepted(self):
        w = RaceWitness()
        box = _Box()
        w.watch(box)
        _run_as("voda-rest-x", lambda: setattr(box, "_val", 1))
        pinned = _pinned(access={"_Box": {"_val": {"write": "unguarded"}}})
        assert w.problems(pinned) == []

    def test_guarded_requirement_enforced(self):
        held = []
        w = RaceWitness(locks_held_fn=lambda: list(held))
        box = _Box()
        w.watch(box, guard_locks=("box._lock",))
        _run_as("voda-rest-x", lambda: setattr(box, "_val", 1))
        pinned = _pinned(access={"_Box": {"_val": {"write": "guarded"}}})
        problems = w.problems(pinned)
        assert problems and "without box._lock held" in problems[0]
        # same access with the lock witnessed as held: accepted
        w2 = RaceWitness(locks_held_fn=lambda: ["box._lock"])
        box2 = _Box()
        w2.watch(box2, guard_locks=("box._lock",))
        _run_as("voda-rest-x", lambda: setattr(box2, "_val", 1))
        assert w2.problems(pinned) == []

    def test_immutable_write_always_violates(self):
        w = RaceWitness()
        box = _Box()
        w.watch(box)
        _run_as("voda-rest-x", lambda: setattr(box, "_frozen", "oops"))
        problems = w.problems(_pinned(immutable={"_Box": ["_frozen"]}))
        assert problems and "immutable-after-__init__" in problems[0]

    def test_immutable_read_free(self):
        w = RaceWitness()
        box = _Box()
        w.watch(box)
        _run_as("voda-rest-x", lambda: getattr(box, "_frozen"))
        assert w.problems(_pinned(immutable={"_Box": ["_frozen"]})) == []

    def test_lock_attrs_not_recorded(self):
        w = RaceWitness()
        box = _Box()
        w.watch(box)

        def touch_lock():
            with box._lock:
                pass

        _run_as("voda-rest-x", touch_lock)
        assert w.observations() == []

    def test_unwatch_restores_class(self):
        w = RaceWitness()
        box = _Box()
        w.watch(box)
        assert type(box) is not _Box
        w.unwatch(box)
        assert type(box) is _Box
        _run_as("voda-rest-x", lambda: setattr(box, "_val", 3))
        assert w.observations() == []

    def test_behavior_transparent_under_watch(self):
        w = RaceWitness()
        box = _Box()
        w.watch(box)
        box._val = 41
        assert box._val == 41
        with box._lock:
            box._val += 1
        assert box._val == 42


class TestLockRemovalFailsSomewhere:
    """Acceptance criterion: removing a lock named in the pinned map
    must fail EITHER the static checker OR the witness — the two halves
    cover for each other."""

    def test_static_half_catches_metrics_lock_removal(self):
        rel, transform, rules = vodarace.VARIANTS["metrics-unlocked-accessor"]
        with open(os.path.join(PKG, rel), encoding="utf-8") as f:
            src = f.read()
        fs = vodarace.race_findings(
            vodarace.analyze_package(PKG, overrides={rel: transform(src)}))
        assert any(f.rule in rules for f in fs)

    def test_witness_half_catches_lock_gone_at_runtime(self):
        # The map pins Scheduler's table accesses as guarded; a run that
        # reaches them without the instrumented lock held (exactly what
        # a deleted `with self._lock:` produces) must fail the witness.
        with open(THREAD_ROLES, encoding="utf-8") as f:
            pinned = json.load(f)
        guarded_attr = None
        decide = pinned["roles"]["decide"]["access"].get("Scheduler", {})
        for attr, kinds in sorted(decide.items()):
            if kinds.get("write") == "guarded":
                guarded_attr = attr
                break
        assert guarded_attr, "map should pin guarded Scheduler writes"
        w = RaceWitness(locks_held_fn=lambda: [])  # lock never held

        class Scheduler:  # noqa: D401 - label stands in for the real one
            pass

        sched = Scheduler()
        w.watch(sched, cls_name="Scheduler",
                guard_locks=("scheduler._lock",))
        _run_as("voda-scheduler-daemon-x",
                lambda: setattr(sched, guarded_attr, 1))
        problems = w.problems(pinned)
        assert problems and "the map pins this access as guarded" in \
            problems[0]
