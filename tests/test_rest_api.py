"""REST API + CLI + composed app: the reference's full service surface
driven over HTTP (SURVEY.md §1: service :55587, scheduler :55588,
allocator :55589; §3.1 submission path; cmd/ CLI).

The app runs a real LocalBackend (supervisor subprocesses on a hermetic
CPU mesh), so the submit->schedule->train->complete loop here is the
genuine article, just tiny.
"""

import io
import json
import time
import urllib.error
import urllib.request
from contextlib import redirect_stdout

import pytest
import yaml

from vodascheduler_tpu import cli

pytestmark = pytest.mark.slow
from vodascheduler_tpu.service.app import VodaApp

TIMEOUT = 180.0


def _get(url):
    with urllib.request.urlopen(url, timeout=10.0) as r:
        return json.loads(r.read())


def _req(url, method, body=None):
    req = urllib.request.Request(url, data=body, method=method)
    with urllib.request.urlopen(req, timeout=10.0) as r:
        return json.loads(r.read())


def _wait(predicate, timeout=TIMEOUT, interval=0.5):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


@pytest.fixture(scope="module")
def app(tmp_path_factory):
    workdir = tmp_path_factory.mktemp("voda")
    app = VodaApp(workdir=str(workdir), hermetic_devices=2, chips=4,
                  rate_limit_seconds=0.5, collector_interval_seconds=5.0,
                  service_port=0, scheduler_port=0, allocator_port=0)
    app.daemon.poll_seconds = 0.2
    app.start()
    yield app
    app.stop()


@pytest.fixture(scope="module")
def urls(app):
    return {
        "service": f"http://127.0.0.1:{app.service_server.port}",
        "scheduler": f"http://127.0.0.1:{app.scheduler_server.port}",
        "allocator": f"http://127.0.0.1:{app.allocator_server.port}",
    }


def _submit(urls, base_name, epochs=1, steps=2):
    spec = {"name": base_name, "model": "mnist_mlp", "global_batch_size": 8,
            "steps_per_epoch": steps,
            "config": {"min_num_chips": 1, "max_num_chips": 2,
                       "epochs": epochs}}
    return _req(f"{urls['service']}/training", "POST",
                yaml.safe_dump(spec).encode())["name"]


def test_submit_trains_and_completes(urls):
    name = _submit(urls, "rest-e2e")
    assert name.startswith("rest-e2e-")

    def done():
        rows = _get(f"{urls['scheduler']}/training")
        return any(r["name"] == name and r["status"] == "Completed"
                   for r in rows)

    assert _wait(done), _get(f"{urls['scheduler']}/training")
    jobs = _get(f"{urls['service']}/training")
    assert any(j["name"] == name and j["status"] == "Completed"
               for j in jobs)


def test_scheduler_endpoints(urls):
    out = _req(f"{urls['scheduler']}/algorithm", "PUT",
               json.dumps({"algorithm": "ElasticTiresias"}).encode())
    assert out["algorithm"] == "ElasticTiresias"
    with pytest.raises(urllib.error.HTTPError):
        _req(f"{urls['scheduler']}/algorithm", "PUT", b'"NoSuchAlgo"')
    out = _req(f"{urls['scheduler']}/ratelimit", "PUT", b"0.5")
    assert out["seconds"] == 0.5
    _req(f"{urls['scheduler']}/algorithm", "PUT", b'"ElasticFIFO"')


def test_metrics_exposition(urls):
    for server in ("service", "scheduler", "allocator"):
        with urllib.request.urlopen(f"{urls[server]}/metrics",
                                    timeout=10.0) as r:
            text = r.read().decode()
        assert "# TYPE" in text
    # scheduler series catalog (reference: doc/prometheus-metrics-exposed.md)
    with urllib.request.urlopen(f"{urls['scheduler']}/metrics",
                                timeout=10.0) as r:
        text = r.read().decode()
    # Series carry the pool const-label (multi-pool composition).
    assert 'voda_scheduler_total_chips{pool="default"} 4' in text


def test_allocation_endpoint_stateless(urls):
    out = _req(f"{urls['allocator']}/allocation", "POST", json.dumps({
        "scheduler_id": "t", "num_chips": 4, "algorithm": "ElasticFIFO",
        "ready_jobs": [],
    }).encode())
    assert out == {}


def test_delete_unknown_job_is_400(urls):
    with pytest.raises(urllib.error.HTTPError) as e:
        _req(f"{urls['service']}/training?name=nope", "DELETE")
    assert e.value.code == 400


def test_cli_flow(urls, tmp_path):
    spec_file = tmp_path / "job.yaml"
    spec_file.write_text(yaml.safe_dump({
        "name": "cli-job", "model": "mnist_mlp", "global_batch_size": 8,
        "steps_per_epoch": 2,
        "config": {"min_num_chips": 1, "max_num_chips": 2, "epochs": 1}}))

    buf = io.StringIO()
    with redirect_stdout(buf):
        cli.main(["--server", urls["service"],
                  "--scheduler-server", urls["scheduler"],
                  "create", "-f", str(spec_file)])
    assert "job created: cli-job-" in buf.getvalue()
    name = buf.getvalue().strip().split(": ")[1]

    buf = io.StringIO()
    with redirect_stdout(buf):
        cli.main(["--server", urls["service"],
                  "--scheduler-server", urls["scheduler"], "get", "jobs"])
    assert name in buf.getvalue()

    buf = io.StringIO()
    with redirect_stdout(buf):
        cli.main(["--server", urls["service"],
                  "--scheduler-server", urls["scheduler"], "get", "status"])
    assert "CHIPS" in buf.getvalue()

    def done():
        rows = _get(f"{urls['scheduler']}/training")
        return any(r["name"] == name and r["status"] == "Completed"
                   for r in rows)
    assert _wait(done)
