"""Property-based invariants for all 8 scheduling algorithms.

The reference enforces these at runtime by panic (validateResult,
pkg/algorithm/utils.go:18-42) and ships zero algorithm tests (SURVEY.md
§4). Here the same invariants are PROPERTIES checked over thousands of
randomized job sets — every allocation any algorithm ever returns must
satisfy them, whatever the mix of pending/running jobs, priorities,
learned curves, and capacity.
"""

import math

import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="property tests need hypothesis (test extra)")
from hypothesis import given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

from tests.helpers import make_job
from vodascheduler_tpu.algorithms import ALGORITHM_NAMES, new_algorithm
from vodascheduler_tpu.common.types import JobStatus


@st.composite
def job_sets(draw):
    n = draw(st.integers(min_value=0, max_value=12))
    jobs = []
    for i in range(n):
        min_chips = draw(st.integers(min_value=1, max_value=8))
        max_chips = draw(st.integers(min_value=min_chips, max_value=16))
        num_chips = draw(st.integers(min_value=min_chips,
                                     max_value=max_chips))
        running = draw(st.booleans())
        # Speedup curve: prior-like (linear) or learned (concave with a
        # random exponent) — covers both sides of the floor-lift auction.
        exponent = draw(st.floats(min_value=0.3, max_value=1.0))
        speedup = {k: float(k) ** exponent for k in range(0, 18)}
        job = make_job(
            f"j{i}",
            submit_time=float(draw(st.integers(0, 10_000))),
            min_chips=min_chips, max_chips=max_chips, num_chips=num_chips,
            priority=draw(st.integers(min_value=0, max_value=2)),
            remaining=float(draw(st.integers(0, 100_000))),
            speedup=speedup,
            first_start_time=(float(draw(st.integers(0, 10_000)))
                              if draw(st.booleans()) else None),
            status=JobStatus.RUNNING if running else JobStatus.WAITING,
        )
        job.metrics.running_seconds = float(draw(st.integers(0, 20_000)))
        job.metrics.seconds_since_restart = float(
            draw(st.integers(0, 8_000)))
        jobs.append(job)
    return jobs


@settings(max_examples=200, deadline=None)
@given(jobs=job_sets(), total=st.integers(min_value=0, max_value=64),
       name=st.sampled_from(ALGORITHM_NAMES))
def test_every_allocation_is_valid(jobs, total, name):
    """The reference's validateResult invariants, as properties:
    every job allocated, nonnegative, zero-or-in-[min,max], sum within
    capacity — plus determinism (same input -> same output)."""
    algo = new_algorithm(name)
    result = algo.schedule(list(jobs), total)

    assert set(result) == {j.name for j in jobs}
    allocated = 0
    for job in jobs:
        got = result[job.name]
        assert isinstance(got, int)
        assert got >= 0
        if got:
            assert job.config.min_num_chips <= got <= job.config.max_num_chips
        allocated += got
    assert allocated <= total

    again = new_algorithm(name).schedule(list(jobs), total)
    assert again == result


@settings(max_examples=100, deadline=None)
@given(jobs=job_sets(), total=st.integers(min_value=1, max_value=64))
def test_elastic_algorithms_leave_no_startable_job_behind(jobs, total):
    """Work-conservation floor for the elastic FIFO family: if capacity
    remains that could start a pending job whose min fits, ElasticFIFO
    must have started it (the reference's round-robin leftover pass)."""
    algo = new_algorithm("ElasticFIFO")
    result = algo.schedule(list(jobs), total)
    free = total - sum(result.values())
    startable = [j for j in jobs
                 if result[j.name] == 0 and j.config.min_num_chips <= free]
    assert not startable, (free, startable, result)


@settings(max_examples=100, deadline=None)
@given(jobs=job_sets(), total=st.integers(min_value=0, max_value=64))
def test_tiresias_priority_ordering_respected(jobs, total):
    """Non-elastic Tiresias allocates in queue order: a lower-priority
    job never holds chips while a HIGHER-priority job that fits inside
    that job's allocation got none (the fixed-NumProc queue discipline,
    tiresias.go:51)."""
    algo = new_algorithm("Tiresias")
    result = algo.schedule(list(jobs), total)
    for starved in jobs:
        if result[starved.name] != 0:
            continue
        for fat in jobs:
            if (fat.priority > starved.priority
                    and result[fat.name] >= starved.config.num_chips
                    and not math.isinf(starved.metrics.first_start_time)):
                # A strictly-lower-priority job holds enough chips to have
                # run the starved higher-priority one instead.
                raise AssertionError(
                    f"{starved.name} (prio {starved.priority}) starved "
                    f"while {fat.name} (prio {fat.priority}) holds "
                    f"{result[fat.name]}")
