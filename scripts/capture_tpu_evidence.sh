#!/bin/sh
# Capture the round's real-TPU evidence in one pass, in dependency order.
# Run from the repo root on a TPU-attached host (each stage's children
# take the chip in turn; nothing here holds it between stages).
#
#   sh scripts/capture_tpu_evidence.sh
#
# Produces / refreshes:
#   doc/e2e_tpu_r4.json            scheduler-driven run on the chip
#   doc/benchmarks_last_good.json  hardware tables (bench.py writes it)
#   doc/benchmarks_r4_raw.json     the full bench.py line, captured
set -x

# 1. Control plane driving the real chip end-to-end (tpu-marked test;
#    skips itself if the accelerator is unreachable).
python -m pytest tests/test_e2e_scheduler.py::test_e2e_scheduler_real_tpu \
    -q -m "tpu" || exit 1

# 2. Full benchmark: replay headline + hardware section (model MFU,
#    flash-vs-XLA, MoE, llama_1b) + elastic-resize cost breakdown.
python bench.py | tail -1 > /tmp/bench_r4_line.json || exit 1
python - <<'EOF'
import json
line = json.load(open("/tmp/bench_r4_line.json"))
out = {
    "note": "Raw bench.py output captured live on the TPU (r4 session).",
    "bench_py_output": line,
}
json.dump(out, open("doc/benchmarks_r4_raw.json", "w"), indent=1)
print("wrote doc/benchmarks_r4_raw.json")
hw = line["detail"].get("hardware", {})
print("hardware keys:", sorted(hw))
for m in hw.get("models", []):
    print("model:", m.get("model"), "mfu:", m.get("mfu"))
for r in hw.get("resize", []):
    print("resize:", r.get("model"), "cost_s:", r.get("resize_cost_seconds"))
EOF
