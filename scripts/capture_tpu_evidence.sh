#!/bin/sh
# Capture the round's real-TPU evidence in one pass, in dependency order.
# Run from the repo root on a TPU-attached host (each stage's children
# take the chip in turn; nothing here holds it between stages).
#
#   sh scripts/capture_tpu_evidence.sh
#
# Produces / refreshes:
#   doc/e2e_tpu_r5.json            scheduler-driven run on the chip
#   doc/benchmarks_last_good.json  hardware tables (bench.py writes it)
#   doc/benchmarks_r5_raw.json     the full bench.py line, captured
#   doc/resize_measured.json       measured restart costs (replay pricing)
#
# Refuses to stamp evidence from a TPU-less host: the e2e test must have
# RUN (not skipped), and the bench hardware section must be live (no
# cached_from/error markers).
#
# AFTER a successful capture (the measured-resize -> replay loop):
#   1. Commit doc/resize_measured.json — replay/restart_costs.py now
#      derives per-family restart pricing from it (provenance switches
#      from "assumed" to "scaled:..." automatically).
#   2. Re-run `python scripts/replay_sweep.py all` — measured costs can
#      move the knee; if it moved, update config.py knob defaults, the
#      guard values in tests/test_replay.py, BASELINE.md and
#      doc/benchmarks.md ("r5 re-base" section conventions).
#   3. Re-derive the p95 floor analysis (doc/benchmarks.md "JCT tail on
#      the true workload") with the re-swept numbers.
#   4. Mark the libtpu series in doc/prometheus-metrics-exposed.md
#      "verified live" (stage 1b below proved the metric names).
#   5. If llama_350m B=16 beat the B=8 bar, note the new flagship batch
#      in BASELINE.md "Measured hardware bars".
set -x

# 1. Control plane driving the real chip end-to-end. -rA makes the
#    skip/pass outcome parseable; a skip means no TPU — abort.
python -m pytest tests/test_e2e_scheduler.py::test_e2e_scheduler_real_tpu \
    -q -rA -m "tpu" | tee /tmp/e2e_tpu_pytest.out
grep -q "PASSED" /tmp/e2e_tpu_pytest.out || {
    echo "e2e TPU test did not PASS (skipped or failed) — not capturing"
    exit 1
}

# 1b. Live libtpu telemetry: SDK metric names verified against this
#     image's libtpu build while real training steps run (VERDICT r4
#     item 4). Over a remote-chip transport the monitoring data plane
#     is absent (chip-local API) — the test then verifies the NAMES and
#     skips the liveness half with that reason, which must not abort
#     the capture (stage 1 already proved the chip is real). After a
#     full PASS on a chip-local host, mark the series list in
#     doc/prometheus-metrics-exposed.md "verified live".
python -m pytest tests/test_tpu_telemetry.py -q -rA -m "tpu" \
    | tee /tmp/telemetry_tpu_pytest.out
# Anchored to the -rA short-summary lines (column 0): a FAILED run's
# traceback may quote the skip-reason string from the test source, and
# an unanchored match would let it through.
grep -Eq "^PASSED|^SKIPPED.*data plane absent" /tmp/telemetry_tpu_pytest.out || {
    echo "live telemetry test did not PASS — not capturing"
    exit 1
}

# 2. Full benchmark: replay headline + hardware section (model MFU,
#    flash-vs-XLA, MoE, llama_1b) + elastic-resize cost breakdown.
#    bench.py prints exactly one stdout line; no pipe, so its exit
#    status is the one tested.
python bench.py > /tmp/bench_r5_line.json || exit 1
python - <<'EOF' || exit 1
import json
import sys

line = json.load(open("/tmp/bench_r5_line.json"))
hw = line["detail"].get("hardware", {})
# Whole-section cache replay (tunnel down before any point ran) is not
# capturable evidence at all.
stale = [k for k in ("cached_from", "error", "live_error") if k in hw]
if stale or not hw.get("models"):
    print(f"hardware section is not live ({stale or 'no models'}) — "
          "refusing to write doc/benchmarks_r5_raw.json")
    sys.exit(1)

# Per-row provenance audit (benchrunner evidence format, doc/
# benchmarks.md): every row must be tagged, and the raw-evidence stamp
# requires at least the measured rows to be genuinely live. Tagged
# cached_from/skipped rows are honest gaps — reported loudly, they fail
# the "complete live capture" bar but not the artifact's integrity.
rows = (hw.get("models", []) + hw.get("attention", [])
        + ([hw["moe"]] if isinstance(hw.get("moe"), dict) else [])
        + hw.get("resize", []) + hw.get("ici", []))
untagged = [r for r in rows if not str(r.get("provenance", "")).startswith(
    ("measured", "cached_from:", "skipped:"))]
if untagged:
    print(f"UNTAGGED rows — evidence plane broken: {untagged}")
    sys.exit(1)
not_live = [r for r in rows if r.get("provenance") != "measured"]
measured_models = [m for m in hw.get("models", [])
                   if m.get("provenance") == "measured"]
if not measured_models:
    print("no live-measured model rows — refusing to stamp raw evidence")
    sys.exit(1)
if not_live:
    print(f"WARNING: {len(not_live)} row(s) are cached/skipped (tagged):")
    for r in not_live:
        print("  ", r.get("provenance"), "-",
              r.get("model") or r.get("point_id") or r.get("seq"))
out = {
    "note": "Raw bench.py output captured live on the TPU (r5 session).",
    "bench_py_output": line,
}
json.dump(out, open("doc/benchmarks_r5_raw.json", "w"), indent=1)
print("wrote doc/benchmarks_r5_raw.json")
for m in hw.get("models", []):
    print("model:", m.get("model"), "mfu:", m.get("mfu"),
          "provenance:", m.get("provenance"))
for r in hw.get("resize", []):
    print("resize:", r.get("model"), "cost_s:", r.get("resize_cost_seconds"),
          "provenance:", r.get("provenance"))

# The measured-restart artifact replay/restart_costs.py derives family
# pricing from: live-measured complete points only (a cached restart
# cost re-stamped as this session's measurement would lie about the
# session). Check it in; then re-run the knee sweep and update the
# replay guards (VERDICT r4 item 2).
from vodascheduler_tpu.replay.restart_costs import _complete
points = [r for r in hw.get("resize", [])
          if _complete(r) and r.get("provenance") == "measured"]
if points:
    json.dump({
        "note": "Measured on-chip by runtime/resize_bench.py via bench.py "
                "(r5 session); consumed by replay/restart_costs.py.",
        "points": points,
    }, open("doc/resize_measured.json", "w"), indent=1)
    print("wrote doc/resize_measured.json with", len(points), "points")
else:
    print("WARNING: no complete live resize points; doc/resize_measured.json "
          "not written")

# The measured-ICI artifact placement/comms.py derives the per-hop link
# bandwidth from (doc/placement.md): live-measured points only, same
# no-restamped-cache rule as the resize artifact above.
ici_points = [r for r in hw.get("ici", [])
              if r.get("ppermute_gbps") and r.get("ring_size")
              and r.get("provenance") == "measured"]
if ici_points:
    json.dump({
        "note": "Measured on-chip by runtime/hwbench.py bench_ici_point "
                "via bench.py; consumed by placement/comms.py link_gbps.",
        "points": ici_points,
    }, open("doc/ici_measured.json", "w"), indent=1)
    print("wrote doc/ici_measured.json with", len(ici_points), "points")
else:
    print("WARNING: no live ICI points; doc/ici_measured.json not written "
          "(placement comms model stays on ASSUMED_LINK_GBPS)")
EOF

# 2b. Evidence-plane self-check: the orchestrator's fake-backend dryrun
#     must pass on the capture host too (fails on any untagged gap).
python -m vodascheduler_tpu.benchrunner.dryrun || {
    echo "benchrunner dryrun failed — evidence plane broken"
    exit 1
}
