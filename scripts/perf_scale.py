#!/usr/bin/env python
"""Decide-path scale harness + CI perf-regression gate.

Synthesizes N-job pools (default N ∈ {100, 1k, 10k}) on a
FakeClusterBackend under a VirtualClock, runs pinned-seed rescheduling
passes through the REAL control plane (admission → allocator →
scheduler → placement), and captures each pass's phase-level
`perf_report` (obs/profile.py) — the per-phase latency-vs-N curves
ROADMAP item 2's vectorization work will be judged against. Wall time is
real compute (the profiler reads time.monotonic, never the virtual
clock), so a curve point is "what a pass of this shape costs in Python
today".

Modes:
  --out doc/perf_baseline.json          regenerate the committed baseline
                                        (`make perf-baseline`; review the
                                        diff like any other artifact)
  --check doc/perf_baseline.json        the CI gate (`make perf-gate`):
                                        re-measure a bounded N set and
                                        fail if the decide phase — or any
                                        sub-phase that costs >= 1 ms in
                                        the baseline — regressed past
                                        baseline * tolerance + slack.
                                        Fresh curves always land in
                                        --fresh-out so a CI failure is
                                        diagnosable from the artifact +
                                        the printed table alone.

The tolerance band (default 3.0x + 25 ms slack) absorbs machine-to-
machine variance; a genuine algorithmic slowdown (the gate's self-test
injects a sleep into the placement phase) lands far outside it.

Churn model: each measured pass is triggered by one job deletion + one
new submission (the coalescing window collects both), so the pass
exercises allocation over the full queue, an incremental placement, and
a small actuation wave — the steady-state shape of a busy pool, not an
empty-to-full stampede (the warm-up pass covers that shape once).
"""

from __future__ import annotations

import argparse
import json
import os
import platform
import random
import statistics
import sys
import time
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_NS = (100, 1000, 10000)
# 5 measured passes per point: enough samples for the p50/p95 columns
# the gate bounds tails with (3 made p95 degenerate-equal to max).
DEFAULT_PASSES = 5
DEFAULT_SEED = 20260803
DEFAULT_RATE_LIMIT = 5.0
DEFAULT_TOLERANCE = 3.0
DEFAULT_SLACK_MS = 25.0
CHIPS_PER_HOST = 8
# Sub-phases cheaper than this in the baseline are not gated — at small
# N they sit in scheduling-noise territory and would flake the gate.
GATE_PHASE_FLOOR_MS = 1.0
# A pure-Python Hungarian bind on a big fleet is O(hosts^3); without the
# native kernel the one-shot defragment probe is skipped (tagged, never
# silent) above this host count.
DEFRAG_PYTHON_HOST_LIMIT = 300

SCHEMA = 2  # v2: mean/max grew p50/p95 (phases: wall_ms_p50/p95)


def build_world(n_jobs: int, seed: int,
                rate_limit_seconds: float = DEFAULT_RATE_LIMIT):
    """One pool sized to its queue: ~1 host per 8 jobs, so demand
    saturates capacity (every pass allocates under contention)."""
    from vodascheduler_tpu.allocator import ResourceAllocator
    from vodascheduler_tpu.cluster.fake import FakeClusterBackend
    from vodascheduler_tpu.common.clock import VirtualClock
    from vodascheduler_tpu.common.events import EventBus
    from vodascheduler_tpu.common.store import JobStore
    from vodascheduler_tpu.obs import tracer as obs_tracer
    from vodascheduler_tpu.placement import PlacementManager
    from vodascheduler_tpu.scheduler import Scheduler
    from vodascheduler_tpu.service import AdmissionService

    clock = VirtualClock(start=1753760000.0)
    tracer = obs_tracer.Tracer(clock=clock)
    store = JobStore()
    bus = EventBus()
    backend = FakeClusterBackend(clock)
    hosts = max(2, n_jobs // CHIPS_PER_HOST)
    for i in range(hosts):
        backend.add_host(f"host-{i}", CHIPS_PER_HOST, announce=False)
    pm = PlacementManager("perf-pool")
    sched = Scheduler("perf-pool", backend, store, ResourceAllocator(store),
                      clock, bus=bus, placement_manager=pm,
                      algorithm="ElasticTiresias",
                      rate_limit_seconds=rate_limit_seconds, tracer=tracer)
    admission = AdmissionService(store, bus, clock)
    return clock, store, backend, sched, admission, random.Random(seed)


def _make_spec(i: int, rng: random.Random):
    from vodascheduler_tpu.common.job import JobConfig, JobSpec
    # Small elastic jobs (the long-tail shape a 10k-job pool actually
    # carries); epochs huge so nothing completes mid-measurement.
    max_chips = rng.choice((1, 2, 2, 4, 4, 8))
    return JobSpec(name=f"perf-{i:05d}", pool="perf-pool",
                   config=JobConfig(min_num_chips=1, max_num_chips=max_chips,
                                    epochs=100000))


def _percentile(values: List[float], q: float) -> float:
    """Nearest-rank percentile (deterministic, no interpolation): the
    smallest sample at or above rank ceil(q * n)."""
    ordered = sorted(values)
    # Integer arithmetic (q as a percent) so 0.95 * 20 == rank 19, not
    # the float-fuzzed 20.
    rank = max(1, (int(q * 100) * len(ordered) + 99) // 100)
    return ordered[rank - 1]


def _agg(values: List[float]) -> Dict[str, float]:
    if not values:
        return {"mean": 0.0, "max": 0.0, "p50": 0.0, "p95": 0.0}
    return {"mean": round(statistics.mean(values), 3),
            "max": round(max(values), 3),
            "p50": round(_percentile(values, 0.50), 3),
            "p95": round(_percentile(values, 0.95), 3)}


def _probe_defragment(sched, hosts: int) -> Dict[str, object]:
    """One-shot full-repack probe: the incremental steady state never
    pays the Hungarian bind, but item 2 needs its cost curve too. Times
    defragment() (and its nested hungarian phase) directly."""
    from vodascheduler_tpu import native
    from vodascheduler_tpu.obs import profile as obs_profile

    if hosts > DEFRAG_PYTHON_HOST_LIMIT and native.get_lib() is None:
        return {"skipped": f"pure-python Hungarian at {hosts} hosts "
                           f"(O(n^3)); build native/_voda_native.so"}
    requests = {j: n for j, n in sched.job_num_chips.items() if n > 0}
    timer = obs_profile.PhaseTimer()
    t0 = time.monotonic()
    with obs_profile.use_timer(timer):
        with timer.phase("placement"):
            sched.placement_manager.defragment(requests)
    wall_ms = (time.monotonic() - t0) * 1000.0
    report = timer.report()
    return {"wall_ms": round(wall_ms, 3),
            "hungarian_wall_ms": report.get("hungarian",
                                            {}).get("wall_ms", 0.0),
            "jobs_placed": len(requests)}


def run_point(n_jobs: int, passes: int = DEFAULT_PASSES,
              seed: int = DEFAULT_SEED,
              inject: Optional[Tuple[str, float]] = None) -> Dict[str, object]:
    """Measure one N: warm-up fill pass, then `passes` churn-triggered
    passes, aggregated from their perf_report records.

    `inject` = (phase, sleep_ms) seeds a deliberate slowdown into the
    named stage ("placement" or "allocate") — the gate's self-test
    (tests/test_perf_profile.py) proves a seeded regression is caught.
    """
    clock, store, backend, sched, admission, rng = build_world(n_jobs, seed)

    if inject is not None:
        phase_name, sleep_ms = inject
        if phase_name == "placement":
            pm = sched.placement_manager
            orig_place = pm.place

            def slow_place(requests):
                time.sleep(sleep_ms / 1000.0)
                return orig_place(requests)

            pm.place = slow_place
        elif phase_name == "allocate":
            orig_alloc = sched.allocator.allocate

            def slow_alloc(request):
                time.sleep(sleep_ms / 1000.0)
                return orig_alloc(request)

            sched.allocator.allocate = slow_alloc
        else:
            raise ValueError(f"injectable phases: placement, allocate "
                             f"(got {phase_name!r})")

    alive: List[str] = []
    for i in range(n_jobs):
        alive.append(admission.create_training_job(_make_spec(i, rng)))
    # Fire the coalesced fill pass (every job after the first landed in
    # one window) and let retriggers settle.
    clock.advance(2 * DEFAULT_RATE_LIMIT + 2.0)
    warmup_seq = (sched.profile_records(1) or [{}])[-1].get("seq", 0)

    next_id = n_jobs
    for _ in range(passes):
        # One deletion + one submission per window: both triggers
        # coalesce into a single churn pass.
        victim = alive.pop(rng.randrange(len(alive)))
        admission.delete_training_job(victim)
        alive.append(admission.create_training_job(
            _make_spec(next_id, rng)))
        next_id += 1
        clock.advance(DEFAULT_RATE_LIMIT + 2.0)

    samples = [r for r in sched.profile_records(0)
               if r["seq"] > warmup_seq]
    if not samples:  # pragma: no cover - harness bug guard
        raise RuntimeError(f"no measured passes at N={n_jobs}")

    phase_stats: Dict[str, Dict[str, List[float]]] = {}
    for rec in samples:
        for name, stats in rec["phases"].items():
            agg = phase_stats.setdefault(name, {"wall": [], "cpu": [],
                                                "count": []})
            agg["wall"].append(stats["wall_ms"])
            agg["cpu"].append(stats["cpu_ms"])
            agg["count"].append(stats["count"])

    hosts = max(2, n_jobs // CHIPS_PER_HOST)
    curve = {
        "n_jobs": n_jobs,
        "hosts": hosts,
        "chips_per_host": CHIPS_PER_HOST,
        "total_chips": hosts * CHIPS_PER_HOST,
        "passes_measured": len(samples),
        "decide_wall_ms": _agg([r["decide_ms"] for r in samples]),
        "actuate_wall_ms": _agg([r["actuate_ms"] for r in samples]),
        "duration_ms": _agg([r["duration_ms"] for r in samples]),
        "cpu_ms": _agg([r["cpu_ms"] for r in samples]),
        "phases": {
            name: {
                "wall_ms_mean": round(statistics.mean(agg["wall"]), 3),
                "wall_ms_max": round(max(agg["wall"]), 3),
                "wall_ms_p50": round(_percentile(agg["wall"], 0.50), 3),
                "wall_ms_p95": round(_percentile(agg["wall"], 0.95), 3),
                "cpu_ms_mean": round(statistics.mean(agg["cpu"]), 3),
                "count_mean": round(statistics.mean(agg["count"]), 2),
            }
            for name, agg in sorted(phase_stats.items())
        },
        "defragment_probe": _probe_defragment(sched, hosts),
    }
    sched.stop()
    return curve


def run_suite(ns=DEFAULT_NS, passes: int = DEFAULT_PASSES,
              seed: int = DEFAULT_SEED, verbose: bool = True) -> dict:
    curves = []
    for n in ns:
        t0 = time.monotonic()
        curve = run_point(n, passes=passes, seed=seed)
        if verbose:
            print(f"perf_scale: N={n}: decide "
                  f"{curve['decide_wall_ms']['mean']}ms mean "
                  f"({time.monotonic() - t0:.1f}s to measure)",
                  file=sys.stderr)
        curves.append(curve)
    return {
        "schema": SCHEMA,
        "tool": "scripts/perf_scale.py",
        "note": ("Per-phase decide/actuate latency-vs-N curves on the "
                 "fake backend (pinned seed), mean/max/p50/p95 per "
                 "phase. Regenerate with `make perf-baseline` and "
                 "review the diff; `make perf-gate` compares a fresh "
                 "bounded-N run (decide mean + p95, >=1ms sub-phase "
                 "means) against this file. doc/observability.md "
                 "'Performance observatory'."),
        "seed": seed,
        "passes": passes,
        "rate_limit_seconds": DEFAULT_RATE_LIMIT,
        "python": platform.python_version(),
        "curves": curves,
    }


# ---- the gate ---------------------------------------------------------------


def compare(baseline: dict, fresh: dict, tolerance: float = DEFAULT_TOLERANCE,
            slack_ms: float = DEFAULT_SLACK_MS) -> List[str]:
    """Regressions of the fresh run vs the baseline; empty = gate
    passes. A fresh value above `base * tolerance + slack_ms` fails —
    the decide MEAN and decide P95 always (the tail is the
    control-plane stall the mean can hide), and the mean of any
    sub-phase whose baseline mean is >= GATE_PHASE_FLOOR_MS (cheaper
    phases are noise-bound)."""
    problems: List[str] = []
    base_by_n = {c["n_jobs"]: c for c in baseline.get("curves", [])}
    for curve in fresh["curves"]:
        n = curve["n_jobs"]
        base = base_by_n.get(n)
        if base is None:
            problems.append(f"N={n}: no baseline curve (regenerate with "
                            f"make perf-baseline)")
            continue

        def check(label: str, fresh_ms: float, base_ms: float) -> None:
            bound = base_ms * tolerance + slack_ms
            verdict = "ok" if fresh_ms <= bound else "REGRESSED"
            print(f"  N={n:>6} {label:<18} base={base_ms:>10.3f}ms "
                  f"fresh={fresh_ms:>10.3f}ms bound={bound:>10.3f}ms "
                  f"{verdict}")
            if fresh_ms > bound:
                problems.append(
                    f"N={n}: {label} regressed: {fresh_ms:.3f}ms vs "
                    f"baseline {base_ms:.3f}ms (bound {bound:.3f}ms)")

        check("decide", curve["decide_wall_ms"]["mean"],
              base["decide_wall_ms"]["mean"])
        # Tail bound: pre-p95 baselines (schema 1) simply skip it.
        base_p95 = base["decide_wall_ms"].get("p95")
        fresh_p95 = curve["decide_wall_ms"].get("p95")
        if base_p95 is not None and fresh_p95 is not None:
            check("decide_p95", fresh_p95, base_p95)
        for name, stats in base.get("phases", {}).items():
            if stats["wall_ms_mean"] < GATE_PHASE_FLOOR_MS:
                continue
            fresh_phase = curve.get("phases", {}).get(name)
            if fresh_phase is None:
                problems.append(f"N={n}: phase {name!r} in baseline but "
                                f"absent from the fresh run")
                continue
            check(name, fresh_phase["wall_ms_mean"], stats["wall_ms_mean"])
    return problems


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="perf_scale",
        description="decide-path scale curves + CI perf-regression gate "
                    "(doc/observability.md 'Performance observatory')")
    parser.add_argument("--ns", default=None,
                        help="comma-separated job counts "
                             f"(default {','.join(map(str, DEFAULT_NS))})")
    parser.add_argument("--passes", type=int, default=DEFAULT_PASSES)
    parser.add_argument("--seed", type=int, default=DEFAULT_SEED)
    parser.add_argument("--out", default=None,
                        help="write the measured curves to this baseline "
                             "file and exit")
    parser.add_argument("--check", default=None, metavar="BASELINE",
                        help="gate mode: compare a fresh run against the "
                             "committed baseline")
    parser.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                        help="allowed fresh/baseline ratio (default 3.0)")
    parser.add_argument("--slack-ms", type=float, default=DEFAULT_SLACK_MS,
                        help="absolute slack added to every bound")
    parser.add_argument("--fresh-out", default=None,
                        help="where --check writes the fresh curves "
                             "(default doc/perf_gate_fresh.json; uploaded "
                             "as a CI artifact on failure)")
    parser.add_argument("--inject-phase", default=None,
                        choices=("placement", "allocate"),
                        help="seed a sleep into this stage (gate "
                             "self-test)")
    parser.add_argument("--inject-ms", type=float, default=0.0)
    args = parser.parse_args(argv)

    ns = (tuple(int(x) for x in args.ns.split(",")) if args.ns
          else DEFAULT_NS)

    if args.check:
        with open(args.check) as f:
            baseline = json.load(f)
        if args.inject_phase:
            # Self-test path: measure with the seeded slowdown.
            curves = [run_point(n, passes=args.passes, seed=args.seed,
                                inject=(args.inject_phase, args.inject_ms))
                      for n in ns]
            fresh = {"schema": SCHEMA, "curves": curves}
        else:
            fresh = run_suite(ns, passes=args.passes, seed=args.seed)
        fresh_out = args.fresh_out or os.path.join(
            os.path.dirname(args.check), "perf_gate_fresh.json")
        with open(fresh_out, "w") as f:
            json.dump(fresh, f, indent=1, sort_keys=True)
        print(f"perf-gate: comparing against {args.check} "
              f"(tolerance x{args.tolerance} + {args.slack_ms}ms slack); "
              f"fresh curves -> {fresh_out}")
        problems = compare(baseline, fresh, tolerance=args.tolerance,
                           slack_ms=args.slack_ms)
        for p in problems:
            print(f"perf-gate: FAIL: {p}")
        print(f"perf-gate: {'FAILED' if problems else 'ok'} "
              f"({len(problems)} regression(s))")
        return 1 if problems else 0

    result = run_suite(ns, passes=args.passes, seed=args.seed)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.out} ({len(result['curves'])} curve(s))")
    else:
        print(json.dumps(result, indent=1, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
