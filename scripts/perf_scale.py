#!/usr/bin/env python
"""Decide-path + ingestion-plane scale harness + CI perf-regression gate.

Synthesizes N-job pools (default N ∈ {100, 1k, 10k}) on a
FakeClusterBackend under a VirtualClock, runs pinned-seed rescheduling
passes through the REAL control plane (admission → allocator →
scheduler → placement), and captures each pass's phase-level
`perf_report` (obs/profile.py) — the per-phase latency-vs-N curves
ROADMAP item 2's vectorization work will be judged against. Wall time is
real compute (the profiler reads time.monotonic, never the virtual
clock), so a curve point is "what a pass of this shape costs in Python
today".

Modes:
  --out doc/perf_baseline.json          regenerate the committed baseline
                                        (`make perf-baseline`; review the
                                        diff like any other artifact)
  --check doc/perf_baseline.json        the CI gate (`make perf-gate`):
                                        re-measure a bounded N set and
                                        fail if the decide phase — or any
                                        sub-phase that costs >= 1 ms in
                                        the baseline — regressed past
                                        baseline * tolerance + slack.
                                        Fresh curves always land in
                                        --fresh-out so a CI failure is
                                        diagnosable from the artifact +
                                        the printed table alone.

The tolerance band (default 3.0x + 25 ms slack) absorbs machine-to-
machine variance; a genuine algorithmic slowdown (the gate's self-test
injects a sleep into the placement phase) lands far outside it.

Churn model: each measured pass is triggered by one job deletion + one
new submission (the coalescing window collects both), so the pass
exercises allocation over the full queue, an incremental placement, and
a small actuation wave — the steady-state shape of a busy pool, not an
empty-to-full stampede (the warm-up pass covers that shape once).

Schema 3 adds the ingestion section (doc/observability.md "Ingestion
plane"): per-N bulk-admission burst curves (per-item p50/p99 through the
REAL AdmissionService batch path: validate -> one store commit -> one
publish_many -> batched scheduler drain), single-request admission
p50/p99, the event-storm-to-quiescent shape (how many coalesced resched
passes a fleet-sized CREATE storm costs, and how long until the pool is
quiet), and read latency from the snapshot cache — sampled by a
concurrent scrape thread WHILE the storm's passes are in flight. The
gate bounds the admission p99 columns with a tighter slack than the
decide phases (sub-ms admission costs would vanish inside the decide
slack), and pins passes-to-quiescent so a coalescing regression (N
events -> N passes) cannot land silently.
"""

from __future__ import annotations

import argparse
import json
import os
import platform
import random
import statistics
import sys
import time
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_NS = (100, 1000, 10000)
# 5 measured passes per point: enough samples for the p50/p95 columns
# the gate bounds tails with (3 made p95 degenerate-equal to max).
DEFAULT_PASSES = 5
DEFAULT_SEED = 20260803
DEFAULT_RATE_LIMIT = 5.0
DEFAULT_TOLERANCE = 3.0
DEFAULT_SLACK_MS = 25.0
CHIPS_PER_HOST = 8
# Sub-phases cheaper than this in the baseline are not gated — at small
# N they sit in scheduling-noise territory and would flake the gate.
GATE_PHASE_FLOOR_MS = 1.0
# A pure-Python Hungarian bind on a big fleet is O(hosts^3); without the
# native kernel the one-shot defragment probe is skipped (tagged, never
# silent) above this host count.
DEFRAG_PYTHON_HOST_LIMIT = 300

SCHEMA = 9  # v2: mean/max grew p50/p95; v3: aggregates grew p99 and the
# suite grew the top-level "ingestion" section (bulk/single admission,
# storm-to-quiescent, snapshot-cache reads); v4: curves grew the
# "placement_scoring" column (the bandwidth-aware objective's fleet
# scoring cost — doc/placement.md); v5: the top-level "fleet" section —
# N jobs routed across >=8 heterogeneous pools, concurrent multi-pool
# decide fan-outs on the fleet executor, per-pool decide p95, fleet
# pass throughput, and router latency (doc/observability.md "Fleet
# decide"); v6: the top-level "fractional" section — the same decide
# curves re-measured on a TOPOLOGY-MODELED pool with a fractional-mix
# queue (sub-host resource classes, interference weights, feasibility
# rounding all live — doc/fractional-sharing.md), so the PR 8 <50 ms
# pin holds with fractional jobs in the vector; v7: the top-level
# "recovery" section (doc/durability.md) — the same decide curves
# re-measured with the write-ahead journal ON (a real file journal:
# every transition/booking/placement append on the decide path is
# paid), journal growth per pass, and the cold crash-recovery time
# (journal replay + backend reconcile) at each N, so journaling can
# never quietly eat the decide budget and recovery stays O(live jobs);
# v8: the top-level "learned" section (doc/learned-models.md) — the
# decide curves with LEARNED-MODEL LOOKUPS ACTIVE in the hot path
# (every job carries a learned fraction doc, the store's model version
# bumps before every pass so each decide pays the batched refresh +
# weight re-derivation), plus the planner-overhead column: the same
# passes with a concurrent what-if shadow plan per churn window, so
# the planner can never quietly inflate the live decide tail; v9: the
# top-level "failover" section (doc/durability.md "Hot standby") —
# journaled decide with a live shipping tailer attached, standby apply
# lag, repeated hot-standby takeovers measured lease-loss -> first
# committed decide (p95 pinned < 1 s at 10k), the cold-recovery
# fastpath-vs-reference A/B (speedup pinned >= 2x at 10k), and the
# bounded fleet cold-recovery row (per-pool parallel replay on an
# executor); also fixes the v7 recovery section's `journal_bytes`
# artifact — now sampled at the kill point (what recovery must read),
# not after the recovery's own compaction truncated the shared file.

# Fleet points measured by default: the gate-bounded small fleet and
# the 100k-job headline (ROADMAP "next order of magnitude").
DEFAULT_FLEET_NS = (16000, 100000)
# 16 heterogeneous pools (>=8 per the fleet acceptance): ~6.3k jobs per
# pool at the 100k headline — the per-GPU-type sharding the reference
# deploys, sized so each pool's decide sits inside the 50 ms pin with
# headroom for scheduling noise while TOTAL fleet capacity covers the
# next order of magnitude.
FLEET_POOLS = 16
FLEET_WORKERS = 8
FLEET_PASSES = 3

# Ingestion measurement shape: the admission slack is deliberately
# tighter than the decide slack — a per-item bulk admission costs
# ~0.05-0.5 ms, so the decide gate's 25-50 ms slack would make its
# bound vacuous. The divisor keeps the two gates one knob.
INGEST_SLACK_DIVISOR = 5.0
# Passes-to-quiescent is a COUNT, not a latency: machine speed cannot
# move it, only a coalescing regression can. The bound still leaves
# room for one extra retrigger window.
INGEST_PASS_BOUND = (2.0, 2)  # fresh <= base * 2 + 2


def build_world(n_jobs: int, seed: int,
                rate_limit_seconds: float = DEFAULT_RATE_LIMIT,
                fractional: bool = False):
    """One pool sized to its queue: ~1 host per 8 jobs, so demand
    saturates capacity (every pass allocates under contention).

    `fractional` (schema 6, doc/fractional-sharing.md): model the pool
    as a 1D host ring topology so the whole fractional plane is live —
    resource-class resolution, within-block feasibility rounding,
    interference weights, co-tenancy pricing, and the backend's
    interference-sensitive physics. The default world stays un-modeled
    (the classic decide curves measure the same code path they always
    did)."""
    from vodascheduler_tpu.allocator import ResourceAllocator
    from vodascheduler_tpu.cluster.fake import FakeClusterBackend
    from vodascheduler_tpu.common.clock import VirtualClock
    from vodascheduler_tpu.common.events import EventBus
    from vodascheduler_tpu.common.store import JobStore
    from vodascheduler_tpu.obs import tracer as obs_tracer
    from vodascheduler_tpu.placement import PlacementManager
    from vodascheduler_tpu.placement.topology import default_pool
    from vodascheduler_tpu.scheduler import Scheduler
    from vodascheduler_tpu.service import AdmissionService

    clock = VirtualClock(start=1753760000.0)
    tracer = obs_tracer.Tracer(clock=clock)
    store = JobStore()
    bus = EventBus()
    backend = FakeClusterBackend(clock)
    hosts = max(2, n_jobs // CHIPS_PER_HOST)
    topology = default_pool(hosts, CHIPS_PER_HOST) if fractional else None
    for i in range(hosts):
        backend.add_host(f"host-{i}", CHIPS_PER_HOST, announce=False)
    if topology is not None:
        backend.set_topology(topology)
    pm = PlacementManager("perf-pool", topology=topology)
    sched = Scheduler("perf-pool", backend, store, ResourceAllocator(store),
                      clock, bus=bus, placement_manager=pm,
                      algorithm="ElasticTiresias",
                      rate_limit_seconds=rate_limit_seconds, tracer=tracer)
    admission = AdmissionService(store, bus, clock)
    return clock, store, backend, sched, admission, random.Random(seed)


def _make_spec(i: int, rng: random.Random, fractional: bool = False):
    from vodascheduler_tpu.common.job import JobConfig, JobSpec
    if fractional:
        # Fractional-mix queue (doc/fractional-sharing.md): a long tail
        # of sub-host tenants (incl. non-power-of-two partitions that
        # only the fractional table admits, and explicit classes) next
        # to whole-host jobs.
        max_chips = rng.choice((1, 2, 2, 3, 4, 5, 8))
        rc = rng.choice(("auto", "auto", "auto", "fractional",
                         "whole_host"))
        if rc == "fractional" and max_chips >= CHIPS_PER_HOST:
            max_chips = CHIPS_PER_HOST - 1
        return JobSpec(name=f"perf-{i:05d}", pool="perf-pool",
                       resource_class=rc,
                       config=JobConfig(min_num_chips=1,
                                        max_num_chips=max_chips,
                                        epochs=100000))
    # Small elastic jobs (the long-tail shape a 10k-job pool actually
    # carries); epochs huge so nothing completes mid-measurement.
    max_chips = rng.choice((1, 2, 2, 4, 4, 8))
    return JobSpec(name=f"perf-{i:05d}", pool="perf-pool",
                   config=JobConfig(min_num_chips=1, max_num_chips=max_chips,
                                    epochs=100000))


def _percentile(values: List[float], q: float) -> float:
    """Nearest-rank percentile — the one shared implementation
    (common/metrics.py), re-exported under the harness's local name."""
    from vodascheduler_tpu.common.metrics import nearest_rank_percentile
    return nearest_rank_percentile(values, q)


def _agg(values: List[float]) -> Dict[str, float]:
    if not values:
        return {"mean": 0.0, "max": 0.0, "p50": 0.0, "p95": 0.0,
                "p99": 0.0}
    return {"mean": round(statistics.mean(values), 3),
            "max": round(max(values), 3),
            "p50": round(_percentile(values, 0.50), 3),
            "p95": round(_percentile(values, 0.95), 3),
            "p99": round(_percentile(values, 0.99), 3)}


def _probe_defragment(sched, hosts: int) -> Dict[str, object]:
    """One-shot full-repack probe: the incremental steady state never
    pays the Hungarian bind, but item 2 needs its cost curve too. Times
    defragment() (and its nested hungarian phase) directly."""
    from vodascheduler_tpu import native
    from vodascheduler_tpu.obs import profile as obs_profile

    if hosts > DEFRAG_PYTHON_HOST_LIMIT and native.get_lib() is None:
        return {"skipped": f"pure-python Hungarian at {hosts} hosts "
                           f"(O(n^3)); build native/_voda_native.so"}
    requests = {j: n for j, n in sched.job_num_chips.items() if n > 0}
    timer = obs_profile.PhaseTimer()
    t0 = time.monotonic()
    with obs_profile.use_timer(timer):
        with timer.phase("placement"):
            sched.placement_manager.defragment(requests)
    wall_ms = (time.monotonic() - t0) * 1000.0
    report = timer.report()
    return {"wall_ms": round(wall_ms, 3),
            "hungarian_wall_ms": report.get("hungarian",
                                            {}).get("wall_ms", 0.0),
            "jobs_placed": len(requests)}


def _probe_placement_scoring(sched) -> Dict[str, object]:
    """One-shot cost probe of the bandwidth-aware scoring plane
    (doc/placement.md) at fleet size: the batch category->weight lookup
    (placement/comms.py weights_for_categories — one memo probe per
    job, one table lookup per distinct category) plus a full fleet
    contiguity/comms re-score (the incremental pass never pays this;
    the probe prices the worst case a cache rebuild costs). The gate
    bounds the total so comms scoring can never quietly eat the decide
    budget item 2 reclaimed."""
    from vodascheduler_tpu.placement import comms as comms_mod

    jobs = list(sched.ready_jobs.values())
    t0 = time.monotonic()
    weights = comms_mod.weights_for_categories([j.category for j in jobs])
    weights_ms = (time.monotonic() - t0) * 1000.0
    pm = sched.placement_manager
    pm.set_comms_weights({j.name: w for j, w in zip(jobs, weights) if w})
    t0 = time.monotonic()
    cross, contig, comms = pm._fleet_stats()
    score_ms = (time.monotonic() - t0) * 1000.0
    return {"jobs": len(jobs),
            "weights_ms": round(weights_ms, 3),
            "fleet_score_ms": round(score_ms, 3),
            "total_ms": round(weights_ms + score_ms, 3),
            "comms_score": comms}


def run_point(n_jobs: int, passes: int = DEFAULT_PASSES,
              seed: int = DEFAULT_SEED,
              inject: Optional[Tuple[str, float]] = None,
              fractional: bool = False) -> Dict[str, object]:
    """Measure one N: warm-up fill pass, then `passes` churn-triggered
    passes, aggregated from their perf_report records.

    `inject` = (phase, sleep_ms) seeds a deliberate slowdown into the
    named stage ("placement" or "allocate") — the gate's self-test
    (tests/test_perf_profile.py) proves a seeded regression is caught.

    `fractional` (schema 6): the same measurement on a topology-modeled
    pool with a fractional-mix queue — the column proving the PR 8
    <50 ms decide pin survives with fractional jobs in the vector.
    """
    clock, store, backend, sched, admission, rng = build_world(
        n_jobs, seed, fractional=fractional)

    if inject is not None:
        phase_name, sleep_ms = inject
        if phase_name == "placement":
            pm = sched.placement_manager
            orig_place = pm.place

            def slow_place(requests):
                time.sleep(sleep_ms / 1000.0)
                return orig_place(requests)

            pm.place = slow_place
        elif phase_name == "allocate":
            orig_alloc = sched.allocator.allocate

            def slow_alloc(request):
                time.sleep(sleep_ms / 1000.0)
                return orig_alloc(request)

            sched.allocator.allocate = slow_alloc
        else:
            raise ValueError(f"injectable phases: placement, allocate "
                             f"(got {phase_name!r})")

    alive: List[str] = []
    for i in range(n_jobs):
        alive.append(admission.create_training_job(
            _make_spec(i, rng, fractional=fractional)))
    # Fire the coalesced fill pass (every job after the first landed in
    # one window) and let retriggers settle.
    clock.advance(2 * DEFAULT_RATE_LIMIT + 2.0)
    warmup_seq = (sched.profile_records(1) or [{}])[-1].get("seq", 0)

    # Freeze the boot heap (the run_fleet_point idiom): the fill minted
    # ~100k+ long-lived objects — and in a full-suite run, earlier
    # points' worlds are still awaiting collection — so gen-2 pauses
    # otherwise land inside measured decide windows as pure
    # measurement-harness artifact, not steady-state cost.
    import gc
    gc.collect()
    gc.freeze()
    try:
        next_id = n_jobs
        for _ in range(passes):
            # One deletion + one submission per window: both triggers
            # coalesce into a single churn pass.
            victim = alive.pop(rng.randrange(len(alive)))
            admission.delete_training_job(victim)
            alive.append(admission.create_training_job(
                _make_spec(next_id, rng, fractional=fractional)))
            next_id += 1
            clock.advance(DEFAULT_RATE_LIMIT + 2.0)

        samples = [r for r in sched.profile_records(0)
                   if r["seq"] > warmup_seq]
        if not samples:  # pragma: no cover - harness bug guard
            raise RuntimeError(f"no measured passes at N={n_jobs}")

        phase_stats: Dict[str, Dict[str, List[float]]] = {}
        for rec in samples:
            for name, stats in rec["phases"].items():
                agg = phase_stats.setdefault(name, {"wall": [], "cpu": [],
                                                    "count": []})
                agg["wall"].append(stats["wall_ms"])
                agg["cpu"].append(stats["cpu_ms"])
                agg["count"].append(stats["count"])

        hosts = max(2, n_jobs // CHIPS_PER_HOST)
        curve = {
            "n_jobs": n_jobs,
            "hosts": hosts,
            "chips_per_host": CHIPS_PER_HOST,
            "total_chips": hosts * CHIPS_PER_HOST,
            "passes_measured": len(samples),
            "decide_wall_ms": _agg([r["decide_ms"] for r in samples]),
            "actuate_wall_ms": _agg([r["actuate_ms"] for r in samples]),
            "duration_ms": _agg([r["duration_ms"] for r in samples]),
            "cpu_ms": _agg([r["cpu_ms"] for r in samples]),
            "phases": {
                name: {
                    "wall_ms_mean": round(statistics.mean(agg["wall"]), 3),
                    "wall_ms_max": round(max(agg["wall"]), 3),
                    "wall_ms_p50": round(_percentile(agg["wall"], 0.50), 3),
                    "wall_ms_p95": round(_percentile(agg["wall"], 0.95), 3),
                    "cpu_ms_mean": round(statistics.mean(agg["cpu"]), 3),
                    "count_mean": round(statistics.mean(agg["count"]), 2),
                }
                for name, agg in sorted(phase_stats.items())
            },
            "defragment_probe": _probe_defragment(sched, hosts),
            "placement_scoring": _probe_placement_scoring(sched),
        }
    finally:
        # An aborted point must not leave the heap frozen for the rest
        # of the suite (every later point would measure against
        # uncollectable prior worlds).
        gc.unfreeze()
    sched.stop()
    return curve


def run_recovery_point(n_jobs: int, passes: int = DEFAULT_PASSES,
                       seed: int = DEFAULT_SEED) -> Dict[str, object]:
    """Measure the durability plane at one N (schema 7,
    doc/durability.md): the decide curve with a REAL file journal wired
    (every write-ahead append on the decide path is paid — the overhead
    the <50 ms pin must absorb), journal growth per churn pass, and the
    cold recovery: drop the scheduler, reopen the journal at the next
    fencing epoch, rebuild + reconcile, and time it."""
    import tempfile

    from vodascheduler_tpu.durability.journal import Journal
    from vodascheduler_tpu.placement import PlacementManager
    from vodascheduler_tpu.scheduler import Scheduler

    clock, store, backend, sched, admission, rng = build_world(
        n_jobs, seed)
    tmp = tempfile.TemporaryDirectory(prefix="voda-perf-journal-")
    journal = Journal(path=os.path.join(tmp.name, "perf-pool.wal"))
    # Attach post-construction: the fill below journals every
    # accept/booking exactly like a journaled-from-birth scheduler.
    sched.journal = journal
    sched.job_num_chips.journal = journal

    alive: List[str] = []
    for i in range(n_jobs):
        alive.append(admission.create_training_job(_make_spec(i, rng)))
    clock.advance(2 * DEFAULT_RATE_LIMIT + 2.0)
    warmup_seq = (sched.profile_records(1) or [{}])[-1].get("seq", 0)
    bytes_after_fill = journal.size_bytes()

    import gc
    gc.collect()
    gc.freeze()
    try:
        next_id = n_jobs
        appends_before = journal._appends
        for _ in range(passes):
            victim = alive.pop(rng.randrange(len(alive)))
            admission.delete_training_job(victim)
            alive.append(admission.create_training_job(
                _make_spec(next_id, rng)))
            next_id += 1
            clock.advance(DEFAULT_RATE_LIMIT + 2.0)
        samples = [r for r in sched.profile_records(0)
                   if r["seq"] > warmup_seq]
        if not samples:  # pragma: no cover - harness bug guard
            raise RuntimeError(f"no journaled passes at N={n_jobs}")
        appends_per_pass = (journal._appends - appends_before) / max(
            1, len(samples))

        # The crash: drop the scheduler, reopen the journal at the next
        # epoch, recover on the same store/backend, time it.
        # journal_bytes is sampled HERE — at the kill point — because it
        # claims to be "what recovery must read": the old sampling point
        # (after recovery) read the shared file AFTER the recovery's own
        # compaction had folded it, reporting a 93-byte segment for a
        # 6.8 MB replay. The snapshot is part of the read too.
        bytes_at_kill = journal.size_bytes()
        snap_path = journal.snapshot_path()
        snapshot_bytes_at_kill = (os.path.getsize(snap_path)
                                  if snap_path and os.path.exists(snap_path)
                                  else 0)
        sched.stop()
        journal.close()
        t0 = time.monotonic()
        journal2 = Journal(path=os.path.join(tmp.name, "perf-pool.wal"),
                           epoch=journal.epoch + 1)
        pm2 = PlacementManager("perf-pool")
        sched2 = Scheduler("perf-pool", backend, store, sched.allocator,
                           clock, bus=sched.bus, placement_manager=pm2,
                           algorithm="ElasticTiresias",
                           rate_limit_seconds=DEFAULT_RATE_LIMIT,
                           journal=journal2, resume=True,
                           tracer=sched.tracer)
        recovery_seconds = time.monotonic() - t0
        report = sched2._last_recovery_report or {}
        point = {
            "n_jobs": n_jobs,
            "passes_measured": len(samples),
            "decide_wall_ms": _agg([r["decide_ms"] for r in samples]),
            "journal_bytes_after_fill": bytes_after_fill,
            "journal_bytes": bytes_at_kill,
            "snapshot_bytes": snapshot_bytes_at_kill,
            "journal_appends_per_pass": round(appends_per_pass, 1),
            "recovery_seconds": round(recovery_seconds, 3),
            "recovery_records_replayed": report.get("records", 0),
            "recovery_divergences": len(report.get("divergences", ())),
            "recovered_jobs": report.get("jobs", 0),
        }
        sched2.stop()
        journal2.close()
    finally:
        gc.unfreeze()
        tmp.cleanup()
    return point


def _build_journaled_world(n_jobs: int, seed: int, workdir: str,
                           lease=None):
    """One filled, journaled pool on a REAL file journal (the
    run_recovery_point idiom, shared by the failover harness)."""
    from vodascheduler_tpu.durability.journal import Journal

    clock, store, backend, sched, admission, rng = build_world(
        n_jobs, seed)
    journal = Journal(path=os.path.join(workdir, "perf-pool.wal"),
                      clock=clock,
                      epoch=(lease.epoch if lease is not None else 1),
                      fence=(lease.current_epoch if lease is not None
                             else None))
    sched.journal = journal
    sched.job_num_chips.journal = journal
    alive: List[str] = []
    for i in range(n_jobs):
        alive.append(admission.create_training_job(_make_spec(i, rng)))
    clock.advance(2 * DEFAULT_RATE_LIMIT + 2.0)
    return clock, store, backend, sched, admission, rng, journal, alive


def _cold_recovery_seconds(n_jobs: int, passes: int, seed: int,
                           fastpath: bool, workdir: str) -> float:
    """One cold crash-recovery measurement on a fresh world: fill,
    churn, kill, recover with the given recovery path — the A/B leg of
    the failover section's speedup row (both paths must rebuild
    identical logical tables; tests/test_failover.py pins that)."""
    from vodascheduler_tpu.durability.journal import Journal
    from vodascheduler_tpu.durability.recover import recover_scheduler
    from vodascheduler_tpu.placement import PlacementManager
    from vodascheduler_tpu.scheduler import Scheduler

    (clock, store, backend, sched, admission, rng, journal,
     alive) = _build_journaled_world(n_jobs, seed, workdir)
    next_id = n_jobs
    for _ in range(passes):
        victim = alive.pop(rng.randrange(len(alive)))
        admission.delete_training_job(victim)
        alive.append(admission.create_training_job(
            _make_spec(next_id, rng)))
        next_id += 1
        clock.advance(DEFAULT_RATE_LIMIT + 2.0)
    sched.stop()
    journal.close()
    t0 = time.monotonic()
    journal2 = Journal(path=os.path.join(workdir, "perf-pool.wal"),
                       epoch=journal.epoch + 1, clock=clock)
    sched2 = Scheduler("perf-pool", backend, store, sched.allocator,
                       clock, bus=sched.bus,
                       placement_manager=PlacementManager("perf-pool"),
                       algorithm="ElasticTiresias",
                       rate_limit_seconds=DEFAULT_RATE_LIMIT,
                       journal=journal2, tracer=sched.tracer)
    recover_scheduler(sched2, fastpath=fastpath)
    seconds = time.monotonic() - t0
    sched2.stop()
    journal2.close()
    return seconds


def run_failover_point(n_jobs: int, passes: int = DEFAULT_PASSES,
                       seed: int = DEFAULT_SEED,
                       takeovers: int = 4) -> Dict[str, object]:
    """Measure the hot-standby failover plane at one N (schema 9,
    doc/durability.md "Hot standby"):

    - journaled decide with a LIVE shipping tailer attached — a
      background thread polls the journal file throughout the churn,
      so the decide tail is measured under real shipping concurrency
      (the 10k p95 must stay under the 50 ms pin);
    - standby apply lag: records the applier was behind at each poll;
    - `takeovers` repeated hot takeovers, each measured end to end —
      leader dead, lease expired, then t0 -> acquire (epoch bump) ->
      final suffix drain -> warm journal open -> reconcile -> first
      committed decide — the p50/p95 the <1 s pin binds;
    - the cold-recovery A/B: the same crash recovered through the
      reference per-record path and the fastpath, on identical worlds
      (the >= 2x speedup row).
    """
    import tempfile
    import threading

    from vodascheduler_tpu.durability.journal import Journal
    from vodascheduler_tpu.durability.leader import FileLease
    from vodascheduler_tpu.durability.shipping import FileTailSource
    from vodascheduler_tpu.durability.standby import (
        PoolStandby,
        finish_takeover,
    )
    from vodascheduler_tpu.placement import PlacementManager
    from vodascheduler_tpu.scheduler import Scheduler

    tmp = tempfile.TemporaryDirectory(prefix="voda-perf-failover-")
    ttl = 15.0
    try:
        lease = FileLease(os.path.join(tmp.name, "lease"), holder="A",
                          ttl_seconds=ttl)
        lease.try_acquire()
        (clock, store, backend, sched, admission, rng, journal,
         alive) = _build_journaled_world(n_jobs, seed, tmp.name,
                                         lease=lease)
        # The FileLease above runs on the wall clock (renewals are
        # irrelevant here; expiry is simulated by a fresh holder's
        # acquire after stopping renewal).
        wal_path = os.path.join(tmp.name, "perf-pool.wal")
        standby = PoolStandby("perf-pool", FileTailSource(wal_path))
        standby.poll()  # bootstrap + catch up on the fill

        import gc
        gc.collect()
        gc.freeze()
        try:
            # Churn passes with the tailer polling CONCURRENTLY.
            warmup_seq = (sched.profile_records(1)
                          or [{}])[-1].get("seq", 0)
            lag_samples: List[float] = []
            stop_ship = threading.Event()

            def shipper():
                while not stop_ship.is_set():
                    lag_samples.append(float(standby.poll()))
                    time.sleep(0.005)

            ship_thread = threading.Thread(target=shipper, daemon=True)
            ship_thread.start()
            next_id = n_jobs
            for _ in range(passes):
                victim = alive.pop(rng.randrange(len(alive)))
                admission.delete_training_job(victim)
                alive.append(admission.create_training_job(
                    _make_spec(next_id, rng)))
                next_id += 1
                clock.advance(DEFAULT_RATE_LIMIT + 2.0)
            stop_ship.set()
            ship_thread.join(timeout=10.0)
            standby.poll()  # drain whatever the churn left
            samples = [r for r in sched.profile_records(0)
                       if r["seq"] > warmup_seq]

            # Repeated hot takeovers. Each round: the leader goes
            # silent, a fresh holder acquires (epoch bump), and the
            # warm standby becomes the next leader — measured t0 (the
            # acquire attempt after lease loss) to Scheduler-ctor
            # return (the first decide is committed by then).
            takeover_ms: List[float] = []
            suffix_counts: List[int] = []
            leader = sched
            for round_no in range(takeovers):
                leader.stop()
                holder = FileLease(os.path.join(tmp.name, "lease"),
                                   holder=f"standby-{round_no}",
                                   ttl_seconds=ttl)
                # The dead leader's lease would expire after its TTL;
                # expire it NOW so the measurement is takeover work,
                # not simulated waiting.
                lease.release()
                t0 = time.monotonic()
                epoch = holder.try_acquire()
                bundle = standby.prepare_takeover()
                journal2 = Journal(wal_path, epoch=epoch,
                                   fence=holder.current_epoch,
                                   clock=clock,
                                   resume_hint=bundle["resume_hint"])
                sched2 = Scheduler(
                    "perf-pool", backend, store, sched.allocator, clock,
                    bus=sched.bus,
                    placement_manager=PlacementManager("perf-pool"),
                    algorithm="ElasticTiresias",
                    rate_limit_seconds=DEFAULT_RATE_LIMIT,
                    journal=journal2, resume=True,
                    recovered_state=bundle["state"],
                    tracer=sched.tracer)
                finish_takeover(sched2, standby, t0, epoch,
                                bundle["suffix_records"])
                takeover_ms.append(
                    sched2._last_takeover["duration_ms"])
                suffix_counts.append(bundle["suffix_records"])
                lease = holder
                leader = sched2
                # Next round's standby attaches fresh (bootstraps from
                # whatever snapshot/segment the takeover left) and one
                # churn window — deliberately NOT polled afterwards —
                # gives the next takeover a live suffix to drain, so
                # the measured budget includes real finish-the-suffix
                # work, not just the epoch bump.
                standby = PoolStandby("perf-pool",
                                      FileTailSource(wal_path))
                standby.poll()
                victim = alive.pop(rng.randrange(len(alive)))
                admission.delete_training_job(victim)
                alive.append(admission.create_training_job(
                    _make_spec(next_id, rng)))
                next_id += 1
                clock.advance(DEFAULT_RATE_LIMIT + 2.0)
            leader.stop()
            journal.close()
        finally:
            gc.unfreeze()

        # Cold-recovery A/B on fresh identical worlds (reference path
        # first so the fastpath's numbers never benefit from cache
        # warmth the reference didn't get).
        with tempfile.TemporaryDirectory(
                prefix="voda-perf-ab-ref-") as ref_dir:
            reference_s = _cold_recovery_seconds(
                n_jobs, passes, seed, fastpath=False, workdir=ref_dir)
        with tempfile.TemporaryDirectory(
                prefix="voda-perf-ab-fast-") as fast_dir:
            fastpath_s = _cold_recovery_seconds(
                n_jobs, passes, seed, fastpath=True, workdir=fast_dir)

        return {
            "n_jobs": n_jobs,
            "passes_measured": len(samples),
            "decide_with_shipping_ms": _agg([r["decide_ms"]
                                             for r in samples]),
            "standby": {
                "polls": len(lag_samples),
                "apply_lag_records_mean": round(
                    statistics.mean(lag_samples), 2) if lag_samples
                else 0.0,
                "apply_lag_records_max": (max(lag_samples)
                                          if lag_samples else 0.0),
            },
            "takeover_ms": _agg(takeover_ms),
            "takeovers": len(takeover_ms),
            "takeover_suffix_records_mean": round(
                statistics.mean(suffix_counts), 1) if suffix_counts
            else 0.0,
            "cold_recovery": {
                "reference_seconds": round(reference_s, 3),
                "fastpath_seconds": round(fastpath_s, 3),
                "speedup": round(reference_s / max(1e-9, fastpath_s), 2),
            },
        }
    finally:
        tmp.cleanup()


def run_fleet_recovery_point(total_jobs: int, n_pools: int = FLEET_POOLS,
                             seed: int = DEFAULT_SEED) -> Dict[str, object]:
    """The bounded fleet cold-recovery row (schema 9): journal every
    pool of a router-filled fleet, kill the whole control plane, and
    recover — per-pool journal replay fanned out on a bounded executor
    (recover.read_states_parallel), then the serial reconcile+resume
    per pool. Reports the parallel replay wall vs the per-pool serial
    sum (what the executor buys is IO/parse overlap — Python-bound
    decode shares the GIL) and the total restart-to-all-pools-deciding
    wall."""
    import tempfile

    from vodascheduler_tpu.durability.journal import Journal
    from vodascheduler_tpu.durability.recover import (
        read_state,
        read_states_parallel,
    )
    from vodascheduler_tpu.placement import PlacementManager
    from vodascheduler_tpu.scheduler import Scheduler

    clock, store, schedulers, fleet, router, admission = build_fleet(
        total_jobs, n_pools, seed)
    rng = random.Random(seed)
    tmp = tempfile.TemporaryDirectory(prefix="voda-perf-fleetrec-")
    try:
        journals: Dict[str, object] = {}
        for name, sched in schedulers.items():
            jnl = Journal(path=os.path.join(tmp.name, f"{name}.wal"),
                          clock=clock)
            sched.journal = jnl
            sched.job_num_chips.journal = jnl
            journals[name] = jnl
        alive: List[str] = []
        next_id = 0
        burst = max(100, min(5000, total_jobs // 10))
        remaining = total_jobs
        while remaining > 0:
            take = min(burst, remaining)
            specs = [_fleet_spec(next_id + k, rng) for k in range(take)]
            next_id += take
            remaining -= take
            results = admission.create_training_jobs(specs)
            assert all("error" not in r for r in results), results[:2]
            alive.extend(r["name"] for r in results)
            clock.advance(1.0)
        clock.advance(10.0)
        fleet.run_fleet_pass()
        for sched in schedulers.values():
            sched.stop()
        fleet.close()
        for jnl in journals.values():
            jnl.close()

        import gc
        gc.collect()
        gc.freeze()
        try:
            t_total = time.monotonic()
            journals2 = {
                name: Journal(path=os.path.join(tmp.name, f"{name}.wal"),
                              epoch=2, clock=clock)
                for name in schedulers}
            # Serial replay sum for the speedup column: re-read each
            # pool's state on fresh handles (cold parse each).
            t_serial = time.monotonic()
            serial_states = {
                name: read_state(Journal(
                    path=os.path.join(tmp.name, f"{name}.wal"),
                    clock=clock))
                for name in schedulers}
            serial_sum_s = time.monotonic() - t_serial
            del serial_states
            t_par = time.monotonic()
            states = read_states_parallel(journals2,
                                          workers=FLEET_WORKERS)
            parallel_replay_s = time.monotonic() - t_par
            allocator = next(iter(schedulers.values())).allocator
            recovered = {}
            for name, old in schedulers.items():
                recovered[name] = Scheduler(
                    name, old.backend, store, allocator, clock,
                    bus=old.bus, placement_manager=PlacementManager(name),
                    algorithm=old.algorithm, rate_limit_seconds=0.0,
                    journal=journals2[name], resume=True,
                    recovered_state=states.get(name),
                    tracer=old.tracer)
            total_s = time.monotonic() - t_total
        finally:
            gc.unfreeze()
        recovered_jobs = sum(len(s.ready_jobs) for s in recovered.values())
        divergences = sum(
            len((s._last_recovery_report or {}).get("divergences", ()))
            for s in recovered.values())
        for s in recovered.values():
            s.stop()
        for jnl in journals2.values():
            jnl.close()
        return {
            "total_jobs": total_jobs,
            "pools": n_pools,
            "workers": FLEET_WORKERS,
            "parallel_replay_seconds": round(parallel_replay_s, 3),
            "serial_replay_sum_seconds": round(serial_sum_s, 3),
            "replay_speedup": round(
                serial_sum_s / max(1e-9, parallel_replay_s), 2),
            "total_recovery_seconds": round(total_s, 3),
            "recovered_jobs": recovered_jobs,
            "recovery_divergences": divergences,
        }
    finally:
        tmp.cleanup()


def run_learned_point(n_jobs: int, passes: int = DEFAULT_PASSES,
                      seed: int = DEFAULT_SEED) -> Dict[str, object]:
    """Measure the learned-model plane at one N (schema 8,
    doc/learned-models.md): the decide curve on a topology-modeled
    pool where EVERY job carries a learned fraction doc and the
    store's model version bumps before every churn pass — so each
    measured decide pays the worst case: one batched job_infos_for
    refresh, blend + weight re-derivation for the whole queue, and
    learned-weight placement scoring. Then the same churn with ONE
    concurrent what-if shadow plan per window (the operator pattern),
    so the planner-overhead column proves the shadow decide does not
    inflate the live tail."""
    import threading

    from vodascheduler_tpu.common.job import (
        category_of,
        shared_base_job_info,
    )

    clock, store, backend, sched, admission, rng = build_world(
        n_jobs, seed, fractional=True)

    alive: List[str] = []
    for i in range(n_jobs):
        alive.append(admission.create_training_job(
            _make_spec(i, rng, fractional=True)))
    clock.advance(2 * DEFAULT_RATE_LIMIT + 2.0)

    # Seed a learned doc per job: a nonzero comms/interference fraction
    # estimate with enough weight to clear the confidence blend, so the
    # scheduler's learned consumption path is live for the WHOLE queue
    # (perf-job categories have no family profile — the learned
    # fraction is the only thing giving them placement weight, which is
    # exactly the learned-weight derivation the column prices).
    def touch_model(name: str) -> None:
        # shared_base_job_info: fraction learning does not fork curve
        # dicts (the collector copies-on-write only when measurements
        # arrive), and 10k forked priors would defeat the allocator's
        # shared-curve dedup — a benchmark artifact, not a real cost.
        info = store.get_job_info(name) or shared_base_job_info(
            name, category_of(name), "perf-pool")
        if info.comms_fraction_weight <= 0.0:
            # First observation. Representative mix, not a pathological
            # all-chatty fleet: a quarter of the tail measures
            # genuinely comms/interference-bound (nonzero placement
            # weight), the rest measures quiet (weight 0) — every job
            # still pays the LOOKUP (fetch, blend, weight derivation),
            # which is what this column prices.
            chatty = rng.random() < 0.25
            info.comms_fraction_est = (0.1 + 0.3 * rng.random()
                                       ) if chatty \
                else 0.01 * rng.random()
            info.interference_fraction_est = (0.1 + 0.2 * rng.random()
                                              ) if chatty \
                else 0.01 * rng.random()
        # Re-touches CONVERGE (one more sample of the same value —
        # what a real collector's steady state lands): the consumer
        # re-fetches and re-blends, but integer weights rarely move.
        info.comms_fraction_weight += 1.0
        info.interference_fraction_weight += 1.0
        info.model_version += 1
        store.upsert_job_info(info)
        store.bump_model_version(name)

    for name in list(sched.ready_jobs):
        touch_model(name)
    # One settle pass absorbs the full-fleet cold refresh (the one-off
    # a consumer pays when it has never blended anything); measured
    # passes then pay the STEADY-STATE shape — a per-pass slice of
    # moved models, the way a real collector cadence lands them.
    admission.delete_training_job(alive.pop())
    clock.advance(DEFAULT_RATE_LIMIT + 2.0)
    slice_size = max(10, min(500, n_jobs // 10))
    warmup_seq = (sched.profile_records(1) or [{}])[-1].get("seq", 0)

    import gc
    gc.collect()
    gc.freeze()
    try:
        def churn(with_planner: bool) -> List[dict]:
            nonlocal next_id
            seq0 = (sched.profile_records(1) or [{}])[-1].get("seq", 0)
            for _ in range(passes):
                victim = alive.pop(rng.randrange(len(alive)))
                admission.delete_training_job(victim)
                alive.append(admission.create_training_job(
                    _make_spec(next_id, rng, fractional=True)))
                next_id += 1
                # Every measured pass digests a fresh slice of moved
                # models (fetch + blend + weight re-derivation for the
                # slice): the steady-state learned-lookup cost a real
                # collector cadence lands on the decide path.
                for name in rng.sample(alive, min(slice_size,
                                                  len(alive))):
                    touch_model(name)
                planner = None
                if with_planner:
                    target = alive[rng.randrange(len(alive))]

                    def plan(job=target):
                        try:
                            t0 = time.monotonic()
                            sched.whatif(job)
                            plan_ms.append(
                                (time.monotonic() - t0) * 1000.0)
                        except Exception:  # noqa: BLE001 - busy-shed is fine
                            pass

                    planner = threading.Thread(target=plan, daemon=True)
                    planner.start()
                clock.advance(DEFAULT_RATE_LIMIT + 2.0)
                if planner is not None:
                    planner.join(timeout=30.0)
            return [r for r in sched.profile_records(0)
                    if r["seq"] > seq0]

        next_id = n_jobs
        plan_ms: List[float] = []
        base_samples = churn(with_planner=False)
        planner_samples = churn(with_planner=True)
        if not base_samples or not planner_samples:
            raise RuntimeError(f"no learned passes at N={n_jobs}")
        point = {
            "n_jobs": n_jobs,
            "passes_measured": len(base_samples),
            "learned_jobs": len(alive),
            "decide_wall_ms": _agg([r["decide_ms"]
                                    for r in base_samples]),
            "planner": {
                "plans": len(plan_ms),
                "plan_ms": _agg(plan_ms),
                "decide_wall_ms": _agg([r["decide_ms"]
                                        for r in planner_samples]),
            },
        }
    finally:
        gc.unfreeze()
    sched.stop()
    return point


def run_learned_point_pristine(n_jobs: int,
                               passes: int = DEFAULT_PASSES,
                               seed: int = DEFAULT_SEED
                               ) -> Dict[str, object]:
    """run_learned_point in a PRISTINE subprocess. The learned column
    carries the suite's tightest absolute pin (<50 ms p95 at 10k), and
    measuring it late in a long-lived suite process adds ~4 ms of pure
    harness artifact: earlier sections' 10k worlds fragment the CPython
    heap and pollute allocator arenas, inflating every later section a
    little (gc.freeze guards collection pauses, not locality). A fresh
    process measures the scheduler, not the suite's heap history —
    same hygiene family as the benchrunner's process-per-point. Falls
    back to in-process measurement (tagged, never silent) if the spawn
    fails."""
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = ("import json, scripts.perf_scale as ps; "
            f"print(json.dumps(ps.run_learned_point({n_jobs}, "
            f"passes={passes}, seed={seed})))")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    try:
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             cwd=repo, capture_output=True, text=True,
                             timeout=900)
        if out.returncode != 0:
            raise RuntimeError(out.stderr.strip()[-500:])
        return json.loads(out.stdout.strip().splitlines()[-1])
    except Exception as e:  # noqa: BLE001 - measure anyway, tagged
        point = run_learned_point(n_jobs, passes=passes, seed=seed)
        point["in_process_fallback"] = f"{type(e).__name__}: {e}"
        return point


def run_ingestion_point(n_jobs: int, seed: int = DEFAULT_SEED,
                        inject_admission_ms: float = 0.0
                        ) -> Dict[str, object]:
    """Measure the ingestion plane at one fleet size (doc/observability.md
    "Ingestion plane"): admit `n_jobs` through the REAL bulk path in
    B-sized bursts (each burst: validate -> one store commit -> one
    publish_many -> one batched scheduler drain), plus a tail of timed
    single-request admissions, then let the storm's coalesced passes run
    to quiescence while a concurrent scrape thread samples the snapshot
    cache.

    `inject_admission_ms` seeds a per-job slowdown into the store commit
    — the gate's ingestion self-test (a seeded admission regression must
    trip the p99 bound the way a placement sleep trips the decide one).
    """
    import threading

    clock, store, backend, sched, admission, rng = build_world(n_jobs, seed)

    if inject_admission_ms > 0:
        orig_insert = store.insert_jobs

        def slow_insert(jobs, infos=()):
            time.sleep(inject_admission_ms * max(1, len(jobs)) / 1000.0)
            orig_insert(jobs, infos)

        store.insert_jobs = slow_insert

    # Warm-up: one admitted job runs the inline fill pass and closes the
    # rate-limit window, so every measured admission below lands inside
    # the window — its cost is validate/commit/publish/drain, never a
    # piggy-backed decide pass (those are measured by run_point).
    admission.create_training_job(_make_spec(0, rng))

    # Freeze the pre-measurement heap (the run_point idiom): in a full
    # suite run, the preceding decide worlds' garbage otherwise lands a
    # gen-2 pause inside one measured burst and mints a phantom p99.
    import gc
    gc.collect()
    gc.freeze()

    try:
        # Single-request admissions: the per-request latency a lone client
        # sees on POST /training.
        singles = min(100, max(10, n_jobs // 10))
        single_ms: List[float] = []
        for i in range(singles):
            t0 = time.monotonic()
            admission.create_training_job(_make_spec(1 + i, rng))
            single_ms.append((time.monotonic() - t0) * 1000.0)

        # Bulk bursts: n_jobs more specs through POST /training/batch's
        # engine, B at a time.
        burst_size = max(10, min(1000, n_jobs // 5))
        burst_ms: List[float] = []
        item_ms: List[float] = []
        next_id = 1 + singles
        remaining = n_jobs
        while remaining > 0:
            take = min(burst_size, remaining)
            specs = [_make_spec(next_id + k, rng) for k in range(take)]
            next_id += take
            remaining -= take
            t0 = time.monotonic()
            results = admission.create_training_jobs(specs)
            dt = (time.monotonic() - t0) * 1000.0
            assert all("error" not in r for r in results)
            burst_ms.append(dt)
            # Amortized per-item cost of the burst — items inside a burst
            # are NOT individually timed, so the aggregate's "p99" is over
            # per-burst means (one sample per burst), not per-item tails.
            item_ms.append(dt / take)

        # Storm -> quiescent: every admission above landed in one rate-limit
        # window; advancing the clock fires the coalesced pass(es). A scrape
        # thread hammers the status snapshot THROUGHOUT — while passes hold
        # the scheduler lock — so the read aggregate is "what a concurrent
        # poller pays mid-pass", served from the version-stamped cache.
        seq_before = (sched.profile_records(1) or [{}])[-1].get("seq", 0)
        reads_during: List[float] = []
        stop_reading = threading.Event()

        def scraper():
            while not stop_reading.is_set():
                t0 = time.monotonic()
                sched.status_table_json()
                reads_during.append((time.monotonic() - t0) * 1000.0)
                time.sleep(0.0005)

        # Warm the snapshot cache first: the very first read after boot
        # builds it under the lock, and with the fill pass in flight that
        # cold sample would wait out the whole pass — a boot artifact, not
        # the cached-read-during-pass cost this column claims to measure.
        sched.status_table_json()
        reader = threading.Thread(target=scraper, daemon=True)
        t_storm = time.monotonic()
        reader.start()
        settle_windows = 0
        while settle_windows < 20:
            clock.advance(DEFAULT_RATE_LIMIT + 2.0)
            settle_windows += 1
            with sched._lock:
                pending = sched._resched_pending
            if not pending and admission.bus.pending(sched.pool_id) == 0:
                break
        quiescent_ms = (time.monotonic() - t_storm) * 1000.0
        stop_reading.set()
        reader.join(timeout=5.0)
        passes = len([r for r in sched.profile_records(0)
                      if r["seq"] > seq_before])

        # Steady-state cached reads: the pool is quiet, the snapshot is
        # warm — this is the ~zero a scrape costs between state changes.
        cached_ms: List[float] = []
        for _ in range(200):
            t0 = time.monotonic()
            sched.status_table_json()
            cached_ms.append((time.monotonic() - t0) * 1000.0)

        point = {
            "n_jobs": n_jobs,
            "burst_size": burst_size,
            "bursts": len(burst_ms),
            "singles": singles,
            "bulk_admit_burst_ms": _agg(burst_ms),
            "bulk_admit_per_item_ms": _agg(item_ms),
            "single_admit_ms": _agg(single_ms),
            "storm": {
                "events": n_jobs + singles + 1,
                "passes_to_quiescent": passes,
                "to_quiescent_ms": round(quiescent_ms, 3),
            },
            "read_during_pass_ms": dict(_agg(reads_during),
                                        count=len(reads_during)),
            "read_cached_ms": _agg(cached_ms),
        }
    finally:
        # An aborted point must not leave the heap frozen for the
        # rest of the suite (see run_point).
        gc.unfreeze()
    sched.stop()
    return point


def build_fleet(total_jobs: int, n_pools: int, seed: int):
    """One heterogeneous fleet: `n_pools` pools (alternating 4- and
    8-chip hosts, a mix of algorithms) over ONE shared store/bus/clock/
    allocator, a FleetRouter in front of admission, and a
    FleetCoordinator fanning decide passes onto a bounded executor —
    the production composition (service/app.py), sized so fleet demand
    saturates fleet capacity. Rate limit 0: every churn trigger decides
    immediately, so measured passes are always full-queue decides."""
    from vodascheduler_tpu.allocator import ResourceAllocator
    from vodascheduler_tpu.cluster.fake import FakeClusterBackend
    from vodascheduler_tpu.common.clock import VirtualClock
    from vodascheduler_tpu.common.events import EventBus
    from vodascheduler_tpu.common.store import JobStore
    from vodascheduler_tpu.obs import tracer as obs_tracer
    from vodascheduler_tpu.placement import PlacementManager
    from vodascheduler_tpu.scheduler import Scheduler
    from vodascheduler_tpu.scheduler.fleet import (
        FleetCoordinator,
        FleetRouter,
    )
    from vodascheduler_tpu.service import AdmissionService

    clock = VirtualClock(start=1753760000.0)
    tracer = obs_tracer.Tracer(clock=clock)
    store = JobStore()
    bus = EventBus()
    allocator = ResourceAllocator(store)
    schedulers = {}
    algorithms = ("ElasticTiresias", "ElasticTiresias", "ElasticFIFO",
                  "ElasticTiresias", "ElasticSRJF")
    per_pool = total_jobs // n_pools
    for i in range(n_pools):
        name = f"fleet-p{i}"
        chips_per_host = 8 if i % 2 == 0 else 4
        backend = FakeClusterBackend(clock)
        hosts = max(2, per_pool // chips_per_host)
        for h in range(hosts):
            backend.add_host(f"{name}-host-{h}", chips_per_host,
                             announce=False)
        pm = PlacementManager(name)
        schedulers[name] = Scheduler(
            name, backend, store, allocator, clock, bus=bus,
            placement_manager=pm, algorithm=algorithms[i % len(algorithms)],
            rate_limit_seconds=0.0, tracer=tracer)
    router = FleetRouter(schedulers, enabled=True, tracer=tracer, bus=bus)
    fleet = FleetCoordinator(schedulers, workers=FLEET_WORKERS,
                             tracer=tracer, router=router)
    admission = AdmissionService(store, bus, clock, router=router)
    return clock, store, schedulers, fleet, router, admission


def _fleet_spec(i: int, rng: random.Random):
    from vodascheduler_tpu.common.job import JobConfig, JobSpec
    max_chips = rng.choice((1, 2, 2, 4, 4, 8))
    # pool "": the router places it by fleet-wide score.
    return JobSpec(name=f"fleet-{i:06d}", pool="",
                   config=JobConfig(min_num_chips=1, max_num_chips=max_chips,
                                    epochs=100000))


def run_fleet_point(total_jobs: int, n_pools: int = FLEET_POOLS,
                    passes: int = FLEET_PASSES,
                    seed: int = DEFAULT_SEED) -> Dict[str, object]:
    """Measure the fleet control plane at one size (schema 5): admit
    `total_jobs` router-placed jobs across `n_pools` heterogeneous
    pools, then run `passes` churn-loaded concurrent decide fan-outs on
    the fleet executor. Reports per-pool decide aggregates (the <50 ms
    pin applies to p95), the fleet pass critical path vs its serial
    sum (what the executor buys), fleet-wide pass throughput, router
    decision latency, and the admission cost of the fill."""
    clock, store, schedulers, fleet, router, admission = build_fleet(
        total_jobs, n_pools, seed)
    rng = random.Random(seed)

    # Fill through the REAL bulk admission path (one store commit + one
    # cross-pool publish per burst; every spec router-placed).
    t_fill = time.monotonic()
    alive: List[str] = []
    next_id = 0
    burst = max(100, min(5000, total_jobs // 10))
    remaining = total_jobs
    while remaining > 0:
        take = min(burst, remaining)
        specs = [_fleet_spec(next_id + k, rng) for k in range(take)]
        next_id += take
        remaining -= take
        results = admission.create_training_jobs(specs)
        assert all("error" not in r for r in results), results[:2]
        alive.extend(r["name"] for r in results)
        clock.advance(1.0)
    fill_s = time.monotonic() - t_fill
    clock.advance(10.0)

    # The fill just minted ~1M long-lived objects (jobs, infos, specs,
    # placements); without a freeze, gen-2 collections rescan all of
    # them and the pauses land inside measured decide windows — pure
    # startup artifact, not steady-state cost. Freeze the post-fill
    # heap (the production idiom for exactly this: move the boot heap
    # out of the collector's working set), measure, unfreeze.
    import gc
    gc.collect()
    gc.freeze()

    # Warm-up fan-out, then measured churn rounds. Two distinct
    # measurements per round, deliberately separated:
    # - per-pool decide cost: the churn-triggered passes run SERIALLY
    #   (rate limit 0 decides inline on the admitting thread), so each
    #   sample is what one pool's decide costs uncontended — the fleet
    #   restatement of the PR 8 <50 ms pin. A GIL-contended wall would
    #   conflate "decide got slower" with "executor width".
    # - fleet fan-out: run_fleet_pass decides EVERY pool concurrently
    #   on the bounded executor; its wall (vs the per-pool serial sum)
    #   is what the fleet executor buys end to end.
    fleet.run_fleet_pass()
    last_seq = {name: (s.profile_records(1) or [{}])[-1].get("seq", 0)
                for name, s in schedulers.items()}
    decide_ms: List[float] = []
    pool_decide: Dict[str, List[float]] = {n: [] for n in schedulers}
    fan_walls: List[float] = []
    fan_serial: List[float] = []

    def _collect_serial() -> None:
        for name, sched in schedulers.items():
            samples = [r for r in sched.profile_records(0)
                       if r["seq"] > last_seq[name]]
            if samples:
                last_seq[name] = samples[-1]["seq"]
            for r in samples:
                decide_ms.append(r["decide_ms"])
                pool_decide[name].append(r["decide_ms"])

    for _ in range(passes):
        for _k in range(n_pools):
            victim = alive.pop(rng.randrange(len(alive)))
            admission.delete_training_job(victim)
        newcomers = [_fleet_spec(next_id + k, rng) for k in range(n_pools)]
        next_id += n_pools
        results = admission.create_training_jobs(newcomers)
        alive.extend(r["name"] for r in results)
        clock.advance(1.0)
        _collect_serial()
        out = fleet.run_fleet_pass()
        fan_walls.append(out["wall_ms"])
        fan_serial.append(sum(out["per_pool_ms"].values()))
        # Drop the fan-out's own (contended) samples from the serial
        # decide aggregate.
        for name, sched in schedulers.items():
            last_seq[name] = (sched.profile_records(1)
                              or [{}])[-1].get("seq", last_seq[name])

    per_pool: Dict[str, Dict[str, object]] = {}
    for name, sched in sorted(schedulers.items()):
        per_pool[name] = {
            "algorithm": sched.algorithm,
            "jobs": len(sched.ready_jobs),
            "total_chips": sched.total_chips,
            "passes": len(pool_decide[name]),
            "decide_ms": _agg(pool_decide[name]),
        }
        sched.stop()
    fleet.close()
    gc.unfreeze()
    wall_mean_s = statistics.mean(fan_walls) / 1000.0
    point = {
        "total_jobs": total_jobs,
        "pools": n_pools,
        "workers": FLEET_WORKERS,
        "fleet_passes": passes,
        "fill_bulk_ms_per_job": round(fill_s * 1000.0 / total_jobs, 4),
        "per_pool_decide_ms": _agg(decide_ms),
        "per_pool": per_pool,
        "fleet_pass_wall_ms": _agg(fan_walls),
        "fleet_pass_serial_sum_ms": _agg(fan_serial),
        "fleet_pass_speedup": round(
            statistics.mean(fan_serial) / max(1e-9,
                                              statistics.mean(fan_walls)),
            2),
        "fleet_throughput_jobs_per_s": round(
            total_jobs / max(1e-9, wall_mean_s), 1),
        "router": router.stats(),
    }
    return point


def run_suite(ns=DEFAULT_NS, passes: int = DEFAULT_PASSES,
              seed: int = DEFAULT_SEED, verbose: bool = True,
              fleet_ns=()) -> dict:
    """The full measurement suite. The fleet section (schema 5) is
    opt-in via `fleet_ns` — the 100k point costs minutes, so only the
    baseline-regen entry (`make perf-baseline` → --fleet-ns) pays it;
    hermetic in-process callers default to none."""
    curves = []
    for n in ns:
        t0 = time.monotonic()
        curve = run_point(n, passes=passes, seed=seed)
        if verbose:
            print(f"perf_scale: N={n}: decide "
                  f"{curve['decide_wall_ms']['mean']}ms mean "
                  f"({time.monotonic() - t0:.1f}s to measure)",
                  file=sys.stderr)
        curves.append(curve)
    ingestion = []
    for n in ns:
        t0 = time.monotonic()
        point = run_ingestion_point(n, seed=seed)
        if verbose:
            print(f"perf_scale: N={n}: ingest bulk "
                  f"{point['bulk_admit_per_item_ms']['p99']}ms/job p99, "
                  f"storm -> quiescent in "
                  f"{point['storm']['passes_to_quiescent']} pass(es) "
                  f"({time.monotonic() - t0:.1f}s to measure)",
                  file=sys.stderr)
        ingestion.append(point)
    fractional = []
    for n in ns:
        t0 = time.monotonic()
        curve = run_point(n, passes=passes, seed=seed, fractional=True)
        if verbose:
            print(f"perf_scale: N={n} (fractional mix): decide "
                  f"{curve['decide_wall_ms']['mean']}ms mean, p95 "
                  f"{curve['decide_wall_ms']['p95']}ms "
                  f"({time.monotonic() - t0:.1f}s to measure)",
                  file=sys.stderr)
        fractional.append(curve)
    recovery = []
    for n in ns:
        t0 = time.monotonic()
        point = run_recovery_point(n, passes=passes, seed=seed)
        if verbose:
            print(f"perf_scale: N={n} (journaled): decide "
                  f"{point['decide_wall_ms']['mean']}ms mean, p95 "
                  f"{point['decide_wall_ms']['p95']}ms; cold recovery "
                  f"{point['recovery_seconds']}s over "
                  f"{point['recovery_records_replayed']} record(s) "
                  f"({time.monotonic() - t0:.1f}s to measure)",
                  file=sys.stderr)
        recovery.append(point)
    learned = []
    for n in ns:
        t0 = time.monotonic()
        # 4x the pass count: this column carries an ABSOLUTE p95 pin,
        # and at 5 passes nearest-rank p95 is degenerate-equal to the
        # max — one noisy pass would pin scheduler-noise, not the tail
        # (the same reasoning that moved DEFAULT_PASSES 3 -> 5).
        point = run_learned_point_pristine(n, passes=4 * passes,
                                           seed=seed)
        if verbose:
            print(f"perf_scale: N={n} (learned lookups): decide "
                  f"{point['decide_wall_ms']['mean']}ms mean, p95 "
                  f"{point['decide_wall_ms']['p95']}ms; with planner p95 "
                  f"{point['planner']['decide_wall_ms']['p95']}ms over "
                  f"{point['planner']['plans']} plan(s) "
                  f"({time.monotonic() - t0:.1f}s to measure)",
                  file=sys.stderr)
        learned.append(point)
    failover = []
    for n in ns:
        t0 = time.monotonic()
        point = run_failover_point(n, passes=passes, seed=seed)
        if verbose:
            print(f"perf_scale: N={n} (failover): takeover p95 "
                  f"{point['takeover_ms']['p95']}ms over "
                  f"{point['takeovers']} takeover(s); decide p95 "
                  f"{point['decide_with_shipping_ms']['p95']}ms with "
                  f"shipping attached; cold recovery "
                  f"{point['cold_recovery']['fastpath_seconds']}s vs "
                  f"{point['cold_recovery']['reference_seconds']}s "
                  f"reference (x{point['cold_recovery']['speedup']}) "
                  f"({time.monotonic() - t0:.1f}s to measure)",
                  file=sys.stderr)
        failover.append(point)
    fleet_recovery = []
    for n in (fleet_ns or ()):
        t0 = time.monotonic()
        point = run_fleet_recovery_point(n, seed=seed)
        if verbose:
            print(f"perf_scale: fleet N={n} (cold recovery): "
                  f"{point['total_recovery_seconds']}s total over "
                  f"{point['pools']} pool(s), replay "
                  f"{point['parallel_replay_seconds']}s parallel vs "
                  f"{point['serial_replay_sum_seconds']}s serial "
                  f"({time.monotonic() - t0:.1f}s to measure)",
                  file=sys.stderr)
        fleet_recovery.append(point)
    fleet = []
    for n in (fleet_ns or ()):
        t0 = time.monotonic()
        point = run_fleet_point(n, seed=seed)
        if verbose:
            print(f"perf_scale: fleet N={n}: per-pool decide "
                  f"{point['per_pool_decide_ms']['p95']}ms p95, fleet pass "
                  f"{point['fleet_pass_wall_ms']['mean']}ms "
                  f"(x{point['fleet_pass_speedup']} vs serial), router p99 "
                  f"{point['router']['route_ms']['p99']}ms "
                  f"({time.monotonic() - t0:.1f}s to measure)",
                  file=sys.stderr)
        fleet.append(point)
    return {
        "schema": SCHEMA,
        "tool": "scripts/perf_scale.py",
        "note": ("Per-phase decide/actuate latency-vs-N curves plus the "
                 "ingestion section (bulk/single admission, storm-to-"
                 "quiescent, snapshot-cache reads) on the fake backend "
                 "(pinned seed), mean/max/p50/p95/p99 per aggregate. "
                 "Regenerate with `make perf-baseline` and review the "
                 "diff; `make perf-gate` compares a fresh bounded-N run "
                 "(decide mean + p95, >=1ms sub-phase means, admission "
                 "p99 columns, passes-to-quiescent) against this file. "
                 "doc/observability.md 'Performance observatory' + "
                 "'Ingestion plane'."),
        "seed": seed,
        "passes": passes,
        "rate_limit_seconds": DEFAULT_RATE_LIMIT,
        "python": platform.python_version(),
        "curves": curves,
        "ingestion": ingestion,
        "fractional": fractional,
        "recovery": recovery,
        "learned": learned,
        "failover": failover,
        "fleet_recovery": fleet_recovery,
        "fleet": fleet,
    }


# ---- the gate ---------------------------------------------------------------


def compare(baseline: dict, fresh: dict, tolerance: float = DEFAULT_TOLERANCE,
            slack_ms: float = DEFAULT_SLACK_MS) -> List[str]:
    """Regressions of the fresh run vs the baseline; empty = gate
    passes. A fresh value above `base * tolerance + slack_ms` fails —
    the decide MEAN and decide P95 always (the tail is the
    control-plane stall the mean can hide), and the mean of any
    sub-phase whose baseline mean is >= GATE_PHASE_FLOOR_MS (cheaper
    phases are noise-bound)."""
    problems: List[str] = []
    base_by_n = {c["n_jobs"]: c for c in baseline.get("curves", [])}
    for curve in fresh.get("curves", []):
        n = curve["n_jobs"]
        base = base_by_n.get(n)
        if base is None:
            problems.append(f"N={n}: no baseline curve (regenerate with "
                            f"make perf-baseline)")
            continue

        def check(label: str, fresh_ms: float, base_ms: float) -> None:
            bound = base_ms * tolerance + slack_ms
            verdict = "ok" if fresh_ms <= bound else "REGRESSED"
            print(f"  N={n:>6} {label:<18} base={base_ms:>10.3f}ms "
                  f"fresh={fresh_ms:>10.3f}ms bound={bound:>10.3f}ms "
                  f"{verdict}")
            if fresh_ms > bound:
                problems.append(
                    f"N={n}: {label} regressed: {fresh_ms:.3f}ms vs "
                    f"baseline {base_ms:.3f}ms (bound {bound:.3f}ms)")

        check("decide", curve["decide_wall_ms"]["mean"],
              base["decide_wall_ms"]["mean"])
        # Tail bound: pre-p95 baselines (schema 1) simply skip it.
        base_p95 = base["decide_wall_ms"].get("p95")
        fresh_p95 = curve["decide_wall_ms"].get("p95")
        if base_p95 is not None and fresh_p95 is not None:
            check("decide_p95", fresh_p95, base_p95)
        for name, stats in base.get("phases", {}).items():
            if stats["wall_ms_mean"] < GATE_PHASE_FLOOR_MS:
                continue
            fresh_phase = curve.get("phases", {}).get(name)
            if fresh_phase is None:
                problems.append(f"N={n}: phase {name!r} in baseline but "
                                f"absent from the fresh run")
                continue
            check(name, fresh_phase["wall_ms_mean"], stats["wall_ms_mean"])
        # Placement-scoring column (schema 4): the comms-weight lookup +
        # fleet re-score probe. Pre-v4 baselines simply skip it.
        base_ps = base.get("placement_scoring")
        fresh_ps = curve.get("placement_scoring")
        if base_ps is not None and fresh_ps is not None:
            check("placement_scoring", fresh_ps["total_ms"],
                  base_ps["total_ms"])

    # Fractional-mix columns (schema 6): the same decide bounds on the
    # topology-modeled fractional-mix world, plus the absolute <50 ms
    # p95 pin at the 10k headline point (the PR 8 decide target must
    # hold WITH fractional jobs in the vector —
    # doc/fractional-sharing.md). Pre-v6 baselines simply skip.
    base_frac = {c["n_jobs"]: c for c in baseline.get("fractional", [])}
    fresh_frac = {c["n_jobs"]: c for c in fresh.get("fractional", [])}
    for n in sorted(fresh_frac):
        fc, bc = fresh_frac[n], base_frac.get(n)
        if bc is None:
            problems.append(f"fractional N={n}: no baseline point "
                            f"(regenerate with make perf-baseline)")
            continue

        def zcheck(label: str, fresh_ms: float, base_ms: float) -> None:
            bound = base_ms * tolerance + slack_ms
            verdict = "ok" if fresh_ms <= bound else "REGRESSED"
            print(f"  Z={n:>6} {label:<18} base={base_ms:>10.3f}ms "
                  f"fresh={fresh_ms:>10.3f}ms bound={bound:>10.3f}ms "
                  f"{verdict}")
            if fresh_ms > bound:
                problems.append(
                    f"fractional N={n}: {label} regressed: "
                    f"{fresh_ms:.3f}ms vs baseline {base_ms:.3f}ms "
                    f"(bound {bound:.3f}ms)")

        zcheck("frac_decide", fc["decide_wall_ms"]["mean"],
               bc["decide_wall_ms"]["mean"])
        zcheck("frac_decide_p95", fc["decide_wall_ms"]["p95"],
               bc["decide_wall_ms"]["p95"])
        if n >= 10000 and fc["decide_wall_ms"]["p95"] >= 50.0:
            problems.append(
                f"fractional N={n}: decide p95 "
                f"{fc['decide_wall_ms']['p95']:.3f}ms breaches the "
                f"absolute 50 ms pin with fractional jobs in the mix")

    # Recovery columns (schema 7, doc/durability.md): the journaled
    # decide curve carries the same relative bounds as the classic one
    # PLUS the absolute <50 ms p95 pin at the 10k point (journaling on
    # must not breach the PR 8 decide target); cold recovery time is
    # bounded relatively with a seconds-scale slack (it is an O(live
    # jobs) replay, not a per-pass latency). Pre-v7 baselines skip.
    base_rec = {c["n_jobs"]: c for c in baseline.get("recovery", [])}
    fresh_rec = {c["n_jobs"]: c for c in fresh.get("recovery", [])}
    for n in sorted(fresh_rec):
        fc, bc = fresh_rec[n], base_rec.get(n)
        if bc is None:
            problems.append(f"recovery N={n}: no baseline point "
                            f"(regenerate with make perf-baseline)")
            continue

        def rcheck(label: str, fresh_ms: float, base_ms: float) -> None:
            bound = base_ms * tolerance + slack_ms
            verdict = "ok" if fresh_ms <= bound else "REGRESSED"
            print(f"  R={n:>6} {label:<18} base={base_ms:>10.3f}ms "
                  f"fresh={fresh_ms:>10.3f}ms bound={bound:>10.3f}ms "
                  f"{verdict}")
            if fresh_ms > bound:
                problems.append(
                    f"recovery N={n}: {label} regressed: "
                    f"{fresh_ms:.3f}ms vs baseline {base_ms:.3f}ms "
                    f"(bound {bound:.3f}ms)")

        rcheck("journaled_decide", fc["decide_wall_ms"]["mean"],
               bc["decide_wall_ms"]["mean"])
        rcheck("journaled_decide_p95", fc["decide_wall_ms"]["p95"],
               bc["decide_wall_ms"]["p95"])
        if n >= 10000 and fc["decide_wall_ms"]["p95"] >= 50.0:
            problems.append(
                f"recovery N={n}: decide p95 "
                f"{fc['decide_wall_ms']['p95']:.3f}ms breaches the "
                f"absolute 50 ms pin with journaling on")
        if n >= 10000 and fc["recovery_seconds"] >= 1.0:
            # The failover acceptance (doc/durability.md "Hot
            # standby"): 10k cold recovery >= 2x faster than the
            # pre-fastpath 1.72 s baseline — absolute-bound at 1 s
            # (0.86 s = exactly 2x, plus measurement slack); the
            # committed artifact carries the tighter pin.
            problems.append(
                f"recovery N={n}: cold recovery "
                f"{fc['recovery_seconds']:.3f}s breaches the absolute "
                f"1 s fastpath bound (2x under the pre-fastpath "
                f"1.72 s baseline)")
        rec_slack_s = max(1.0, slack_ms / 25.0)
        base_s = bc["recovery_seconds"]
        fresh_s = fc["recovery_seconds"]
        bound_s = base_s * tolerance + rec_slack_s
        verdict = "ok" if fresh_s <= bound_s else "REGRESSED"
        print(f"  R={n:>6} {'cold_recovery':<18} base={base_s:>9.3f}s "
              f"fresh={fresh_s:>9.3f}s bound={bound_s:>9.3f}s  {verdict}")
        if fresh_s > bound_s:
            problems.append(
                f"recovery N={n}: cold recovery regressed: "
                f"{fresh_s:.3f}s vs baseline {base_s:.3f}s "
                f"(bound {bound_s:.3f}s)")

    # Learned columns (schema 8, doc/learned-models.md): the decide
    # curve with learned-model lookups forced live every pass carries
    # the same relative bounds PLUS the absolute <50 ms p95 pin at the
    # 10k point (the PR 8 decide target must hold with the learned
    # plane in the hot path); the planner column bounds the
    # with-planner decide p95 against the no-planner one — the what-if
    # shadow decide must never inflate the live tail past the shared
    # tolerance. Pre-v8 baselines simply skip.
    base_learn = {c["n_jobs"]: c for c in baseline.get("learned", [])}
    fresh_learn = {c["n_jobs"]: c for c in fresh.get("learned", [])}
    for n in sorted(fresh_learn):
        fc, bc = fresh_learn[n], base_learn.get(n)
        if bc is None:
            problems.append(f"learned N={n}: no baseline point "
                            f"(regenerate with make perf-baseline)")
            continue

        def lcheck(label: str, fresh_ms: float, base_ms: float) -> None:
            bound = base_ms * tolerance + slack_ms
            verdict = "ok" if fresh_ms <= bound else "REGRESSED"
            print(f"  L={n:>6} {label:<18} base={base_ms:>10.3f}ms "
                  f"fresh={fresh_ms:>10.3f}ms bound={bound:>10.3f}ms "
                  f"{verdict}")
            if fresh_ms > bound:
                problems.append(
                    f"learned N={n}: {label} regressed: "
                    f"{fresh_ms:.3f}ms vs baseline {base_ms:.3f}ms "
                    f"(bound {bound:.3f}ms)")

        lcheck("learned_decide", fc["decide_wall_ms"]["mean"],
               bc["decide_wall_ms"]["mean"])
        lcheck("learned_decide_p95", fc["decide_wall_ms"]["p95"],
               bc["decide_wall_ms"]["p95"])
        if n >= 10000 and fc["decide_wall_ms"]["p95"] >= 50.0:
            problems.append(
                f"learned N={n}: decide p95 "
                f"{fc['decide_wall_ms']['p95']:.3f}ms breaches the "
                f"absolute 50 ms pin with learned lookups in the hot "
                f"path")
        # Planner overhead: the live decide tail with a concurrent
        # shadow plan per window, bounded against THIS RUN's no-planner
        # tail (same machine, same moment — a cross-run bound would
        # conflate machine speed with planner cost). The band is
        # tighter than the cross-run tolerance (x1.5 + slack): the
        # pass-yielding planner (replay/whatif.py _yield_to_passes)
        # should keep the tails near-identical, with slack for the
        # residual GIL race when a pass starts mid-plan.
        live_p95 = fc["decide_wall_ms"]["p95"]
        plan_p95 = fc["planner"]["decide_wall_ms"]["p95"]
        bound = live_p95 * 1.5 + slack_ms
        verdict = "ok" if plan_p95 <= bound else "REGRESSED"
        print(f"  L={n:>6} {'planner_overhead':<18} "
              f"base={live_p95:>10.3f}ms fresh={plan_p95:>10.3f}ms "
              f"bound={bound:>10.3f}ms {verdict}")
        if plan_p95 > bound:
            problems.append(
                f"learned N={n}: what-if planner inflates live decide "
                f"p95: {plan_p95:.3f}ms vs {live_p95:.3f}ms without "
                f"(bound {bound:.3f}ms)")

    # Failover columns (schema 9, doc/durability.md "Hot standby"):
    # the takeover budget and the decide-with-shipping tail carry the
    # same relative bounds as the other latency columns PLUS the
    # absolute pins at the 10k point (takeover p95 < 1 s; decide p95
    # < 50 ms with the tailer attached); the cold-recovery fastpath
    # must keep its >= 2x A/B win. Pre-v9 baselines simply skip.
    base_fo = {c["n_jobs"]: c for c in baseline.get("failover", [])}
    fresh_fo = {c["n_jobs"]: c for c in fresh.get("failover", [])}
    for n in sorted(fresh_fo):
        fc, bc = fresh_fo[n], base_fo.get(n)
        if bc is None:
            problems.append(f"failover N={n}: no baseline point "
                            f"(regenerate with make perf-baseline)")
            continue

        def focheck(label: str, fresh_ms: float, base_ms: float) -> None:
            bound = base_ms * tolerance + slack_ms
            verdict = "ok" if fresh_ms <= bound else "REGRESSED"
            print(f"  H={n:>6} {label:<18} base={base_ms:>10.3f}ms "
                  f"fresh={fresh_ms:>10.3f}ms bound={bound:>10.3f}ms "
                  f"{verdict}")
            if fresh_ms > bound:
                problems.append(
                    f"failover N={n}: {label} regressed: "
                    f"{fresh_ms:.3f}ms vs baseline {base_ms:.3f}ms "
                    f"(bound {bound:.3f}ms)")

        focheck("takeover_p95", fc["takeover_ms"]["p95"],
                bc["takeover_ms"]["p95"])
        focheck("ship_decide_p95", fc["decide_with_shipping_ms"]["p95"],
                bc["decide_with_shipping_ms"]["p95"])
        if n >= 10000 and fc["takeover_ms"]["p95"] >= 1000.0:
            problems.append(
                f"failover N={n}: takeover p95 "
                f"{fc['takeover_ms']['p95']:.1f}ms breaches the "
                f"absolute 1 s budget (lease-loss -> first committed "
                f"decide)")
        if n >= 10000 and fc["decide_with_shipping_ms"]["p95"] >= 50.0:
            problems.append(
                f"failover N={n}: decide p95 "
                f"{fc['decide_with_shipping_ms']['p95']:.3f}ms breaches "
                f"the absolute 50 ms pin with shipping attached")
        # The A/B row isolates the recovery PROTOCOL win (batched
        # appends / single jpass / fold vs per-record): both legs share
        # the new decode/encode infrastructure, so the floor here is
        # 1.5x; the headline >= 2x acceptance is measured against the
        # PRE-fastpath committed baseline (PR 13's 1.72 s at 10k) and
        # bound as the absolute recovery_seconds pin below + the
        # committed-artifact test (tests/test_failover.py).
        speedup = fc["cold_recovery"]["speedup"]
        base_speedup = bc["cold_recovery"]["speedup"]
        floor = 1.5 if n >= 10000 else 1.0
        verdict = "ok" if speedup >= floor else "REGRESSED"
        print(f"  H={n:>6} {'recovery_speedup':<18} "
              f"base={base_speedup:>9.2f}x fresh={speedup:>9.2f}x "
              f"floor={floor:>9.2f}x  {verdict}")
        if speedup < floor:
            problems.append(
                f"failover N={n}: cold-recovery fastpath speedup "
                f"{speedup:.2f}x fell under the {floor:.1f}x floor "
                f"(reference {fc['cold_recovery']['reference_seconds']}s "
                f"vs fastpath "
                f"{fc['cold_recovery']['fastpath_seconds']}s)")

    # Fleet cold-recovery row (schema 9): bounded relatively — the
    # total restart wall and the parallel replay leg.
    base_fr = {c["total_jobs"]: c
               for c in baseline.get("fleet_recovery", [])}
    fresh_fr = {c["total_jobs"]: c for c in fresh.get("fleet_recovery", [])}
    for n in sorted(fresh_fr):
        fc, bc = fresh_fr[n], base_fr.get(n)
        if bc is None:
            problems.append(f"fleet_recovery N={n}: no baseline point "
                            f"(regenerate with make perf-baseline)")
            continue
        rec_slack_s = max(1.0, slack_ms / 25.0)
        for label in ("total_recovery_seconds",
                      "parallel_replay_seconds"):
            base_s, fresh_s = bc[label], fc[label]
            bound_s = base_s * tolerance + rec_slack_s
            verdict = "ok" if fresh_s <= bound_s else "REGRESSED"
            print(f"  H={n:>6} {label:<24} base={base_s:>8.3f}s "
                  f"fresh={fresh_s:>8.3f}s bound={bound_s:>8.3f}s "
                  f"{verdict}")
            if fresh_s > bound_s:
                problems.append(
                    f"fleet_recovery N={n}: {label} regressed: "
                    f"{fresh_s:.3f}s vs baseline {base_s:.3f}s "
                    f"(bound {bound_s:.3f}s)")
        if fc["recovery_divergences"] > bc["recovery_divergences"]:
            problems.append(
                f"fleet_recovery N={n}: recovery divergences grew "
                f"{bc['recovery_divergences']} -> "
                f"{fc['recovery_divergences']} (a journaling gap, not "
                f"a latency regression)")

    # Ingestion columns (schema 3): admission p99 bounds use a tighter
    # slack (sub-ms costs would vanish inside the decide slack);
    # passes-to-quiescent is a count bound — only a coalescing
    # regression can move it.
    base_ing = {c["n_jobs"]: c for c in baseline.get("ingestion", [])}
    fresh_ing = {c["n_jobs"]: c for c in fresh.get("ingestion", [])}
    if base_ing and not fresh_ing:
        # The decide-phase inject self-test measures no ingestion; say
        # so rather than silently narrowing the gate.
        print("  (ingestion section absent from the fresh run — "
              "admission columns not compared)")
    ing_slack = slack_ms / INGEST_SLACK_DIVISOR
    for n in sorted(fresh_ing):
        fc, bc = fresh_ing[n], base_ing.get(n)
        if bc is None:
            problems.append(f"N={n}: no baseline ingestion point "
                            f"(regenerate with make perf-baseline)")
            continue

        def icheck(label: str, fresh_ms: float, base_ms: float) -> None:
            bound = base_ms * tolerance + ing_slack
            verdict = "ok" if fresh_ms <= bound else "REGRESSED"
            print(f"  N={n:>6} {label:<18} base={base_ms:>10.3f}ms "
                  f"fresh={fresh_ms:>10.3f}ms bound={bound:>10.3f}ms "
                  f"{verdict}")
            if fresh_ms > bound:
                problems.append(
                    f"N={n}: {label} regressed: {fresh_ms:.3f}ms vs "
                    f"baseline {base_ms:.3f}ms (bound {bound:.3f}ms)")

        icheck("ingest_bulk_p99", fc["bulk_admit_per_item_ms"]["p99"],
               bc["bulk_admit_per_item_ms"]["p99"])
        icheck("ingest_single_p99", fc["single_admit_ms"]["p99"],
               bc["single_admit_ms"]["p99"])
        if fc["read_during_pass_ms"].get("count", 0):
            icheck("ingest_read_p99", fc["read_during_pass_ms"]["p99"],
                   bc["read_during_pass_ms"]["p99"])
        ratio, extra = INGEST_PASS_BOUND
        base_passes = bc["storm"]["passes_to_quiescent"]
        fresh_passes = fc["storm"]["passes_to_quiescent"]
        bound_passes = base_passes * ratio + extra
        verdict = "ok" if fresh_passes <= bound_passes else "REGRESSED"
        print(f"  N={n:>6} {'storm_passes':<18} base={base_passes:>10} "
              f"fresh={fresh_passes:>10} bound={bound_passes:>10.0f}   "
              f"{verdict}")
        if fresh_passes > bound_passes:
            problems.append(
                f"N={n}: storm coalescing regressed: {fresh_passes} "
                f"passes to quiescent vs baseline {base_passes} "
                f"(bound {bound_passes:.0f})")

    # Fleet columns (schema 5): the per-pool decide p95 carries BOTH a
    # relative bound and the absolute <50 ms acceptance pin (the fleet
    # restatement of the PR 8 decide target); the fan-out wall and the
    # router p99 are bounded like the other latency columns (router at
    # the ingestion slack — routing is sub-ms). Pre-v5 baselines skip.
    base_fleet = {c["total_jobs"]: c for c in baseline.get("fleet", [])}
    fresh_fleet = {c["total_jobs"]: c for c in fresh.get("fleet", [])}
    for n in sorted(fresh_fleet):
        fc, bc = fresh_fleet[n], base_fleet.get(n)
        if bc is None:
            problems.append(f"fleet N={n}: no baseline fleet point "
                            f"(regenerate with make perf-baseline)")
            continue

        def fcheck(label: str, fresh_ms: float, base_ms: float,
                   slack: float = slack_ms) -> None:
            bound = base_ms * tolerance + slack
            verdict = "ok" if fresh_ms <= bound else "REGRESSED"
            print(f"  F={n:>6} {label:<18} base={base_ms:>10.3f}ms "
                  f"fresh={fresh_ms:>10.3f}ms bound={bound:>10.3f}ms "
                  f"{verdict}")
            if fresh_ms > bound:
                problems.append(
                    f"fleet N={n}: {label} regressed: {fresh_ms:.3f}ms vs "
                    f"baseline {base_ms:.3f}ms (bound {bound:.3f}ms)")

        fcheck("fleet_decide_p95", fc["per_pool_decide_ms"]["p95"],
               bc["per_pool_decide_ms"]["p95"])
        # The absolute acceptance pin binds the 100k headline point
        # (measured at baseline-regen time; tier-1 also pins the
        # committed artifact) — not the bounded gate point, whose small
        # absolute numbers sit inside CI scheduling noise.
        if n >= 100000 and fc["per_pool_decide_ms"]["p95"] >= 50.0:
            problems.append(
                f"fleet N={n}: per-pool decide p95 "
                f"{fc['per_pool_decide_ms']['p95']:.3f}ms breaches the "
                f"absolute 50 ms fleet pin")
        fcheck("fleet_pass_wall", fc["fleet_pass_wall_ms"]["mean"],
               bc["fleet_pass_wall_ms"]["mean"])
        fcheck("router_p99", fc["router"]["route_ms"]["p99"],
               bc["router"]["route_ms"]["p99"], slack=ing_slack)
    return problems


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="perf_scale",
        description="decide-path scale curves + CI perf-regression gate "
                    "(doc/observability.md 'Performance observatory')")
    parser.add_argument("--ns", default=None,
                        help="comma-separated job counts "
                             f"(default {','.join(map(str, DEFAULT_NS))})")
    parser.add_argument("--fleet-ns", default=None,
                        help="comma-separated FLEET job totals (schema 5). "
                             "Omitted = no fleet section (the 100k point "
                             "costs minutes); make perf-baseline passes "
                             f"{','.join(map(str, DEFAULT_FLEET_NS))} and "
                             "make perf-gate re-measures the bounded "
                             f"{DEFAULT_FLEET_NS[0]} point")
    parser.add_argument("--passes", type=int, default=DEFAULT_PASSES)
    parser.add_argument("--seed", type=int, default=DEFAULT_SEED)
    parser.add_argument("--out", default=None,
                        help="write the measured curves to this baseline "
                             "file and exit")
    parser.add_argument("--check", default=None, metavar="BASELINE",
                        help="gate mode: compare a fresh run against the "
                             "committed baseline")
    parser.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                        help="allowed fresh/baseline ratio (default 3.0)")
    parser.add_argument("--slack-ms", type=float, default=DEFAULT_SLACK_MS,
                        help="absolute slack added to every bound")
    parser.add_argument("--fresh-out", default=None,
                        help="where --check writes the fresh curves "
                             "(default doc/perf_gate_fresh.json; uploaded "
                             "as a CI artifact on failure)")
    parser.add_argument("--failover-only", action="store_true",
                        help="run just the schema-9 failover point(s) "
                             "for --ns and print them (make "
                             "failover-bench)")
    parser.add_argument("--inject-phase", default=None,
                        choices=("placement", "allocate"),
                        help="seed a sleep into this stage (gate "
                             "self-test)")
    parser.add_argument("--inject-ms", type=float, default=0.0)
    parser.add_argument("--inject-admission-ms", type=float, default=0.0,
                        help="seed a per-job sleep into the bulk store "
                             "commit (ingestion-gate self-test)")
    args = parser.parse_args(argv)

    ns = (tuple(int(x) for x in args.ns.split(",")) if args.ns
          else DEFAULT_NS)
    if args.fleet_ns is None or args.fleet_ns.strip().lower() == "none":
        fleet_ns = ()
    else:
        fleet_ns = tuple(int(x) for x in args.fleet_ns.split(","))

    if args.failover_only:
        points = []
        for n in ns:
            t0 = time.monotonic()
            point = run_failover_point(n, passes=args.passes,
                                       seed=args.seed)
            print(f"failover-bench: N={n}: takeover p95 "
                  f"{point['takeover_ms']['p95']}ms, decide p95 "
                  f"{point['decide_with_shipping_ms']['p95']}ms with "
                  f"shipping, cold recovery "
                  f"x{point['cold_recovery']['speedup']} vs reference "
                  f"({time.monotonic() - t0:.1f}s to measure)",
                  file=sys.stderr)
            points.append(point)
        print(json.dumps({"schema": SCHEMA, "failover": points},
                         indent=1, sort_keys=True))
        return 0

    if args.check:
        with open(args.check) as f:
            baseline = json.load(f)
        if args.inject_phase:
            # Self-test path: measure with the seeded slowdown.
            curves = [run_point(n, passes=args.passes, seed=args.seed,
                                inject=(args.inject_phase, args.inject_ms))
                      for n in ns]
            fresh = {"schema": SCHEMA, "curves": curves}
        elif args.inject_admission_ms:
            # Ingestion self-test path: only the admission columns are
            # re-measured, with the seeded per-job commit slowdown.
            fresh = {"schema": SCHEMA, "curves": [],
                     "ingestion": [run_ingestion_point(
                         n, seed=args.seed,
                         inject_admission_ms=args.inject_admission_ms)
                         for n in ns]}
        else:
            fresh = run_suite(ns, passes=args.passes, seed=args.seed,
                              fleet_ns=fleet_ns)
        fresh_out = args.fresh_out or os.path.join(
            os.path.dirname(args.check), "perf_gate_fresh.json")
        with open(fresh_out, "w") as f:
            json.dump(fresh, f, indent=1, sort_keys=True)
        print(f"perf-gate: comparing against {args.check} "
              f"(tolerance x{args.tolerance} + {args.slack_ms}ms slack); "
              f"fresh curves -> {fresh_out}")
        problems = compare(baseline, fresh, tolerance=args.tolerance,
                           slack_ms=args.slack_ms)
        for p in problems:
            print(f"perf-gate: FAIL: {p}")
        print(f"perf-gate: {'FAILED' if problems else 'ok'} "
              f"({len(problems)} regression(s))")
        return 1 if problems else 0

    result = run_suite(ns, passes=args.passes, seed=args.seed,
                       fleet_ns=fleet_ns)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.out} ({len(result['curves'])} curve(s))")
    else:
        print(json.dumps(result, indent=1, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
