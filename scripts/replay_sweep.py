"""Headline-knob sweep on the TRUE replay workload.

Re-derives the rate_limit x hysteresis x cooldown knee and the 8-seed
robustness panel (doc/benchmarks.md methodology) — required after any
change to replay pricing or workload simulation. r7's trigger:
critical-path actuation pricing (the concurrent actuation plane) — the
replay now charges every pass its per-wave-max actuation seconds
against the next rate-limit window, where it previously charged ZERO
(the scheduler could reschedule infinitely fast compared to a live
control plane; the pre-wave serial engine would have charged the SUM,
even worse). Passes are no longer free, so the knee re-balances toward
fewer, better-timed passes. r6's trigger: two-tier resize pricing
(doc/elastic-resize.md) — same-host resizes are in-place live reshards
at a fraction of the cold checkpoint-restart cost, and in-place resizes
no longer re-arm the preemption lease. r5's trigger was the
profile-registration race fix (simulator._submit on_admitted), which
revealed 29/64 headline-trace jobs had been simulating the default
60 s-epoch toy profile.

Usage:
  python scripts/replay_sweep.py knee    # pinned-seed knob sweep
  python scripts/replay_sweep.py panel   # 8-seed panel at chosen knobs
  python scripts/replay_sweep.py all     # both; writes doc/replay_sweep_r7.json
"""

from __future__ import annotations

import itertools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from vodascheduler_tpu.placement import PoolTopology  # noqa: E402
from vodascheduler_tpu.replay import ReplayHarness, philly_like_trace  # noqa: E402
from vodascheduler_tpu.replay.simulator import config5_preemptions  # noqa: E402

PINNED_SEED = 20260729
PANEL_SEEDS = (PINNED_SEED, 7, 42, 101, 202, 303, 404, 505)

RATES = (15.0, 20.0, 30.0, 45.0)
HYSTERESIS = (1.0, 1.5, 2.0)
COOLDOWNS = (60.0, 120.0, 300.0)


def run_one(seed: int, rate: float, hyst: float, cooldown: float,
            num_jobs: int = 64, dims=(4, 4, 4)) -> dict:
    trace = philly_like_trace(num_jobs=num_jobs, seed=seed, max_job_chips=64)
    topo = PoolTopology(torus_dims=dims, host_block=(2, 2, 1))
    r = ReplayHarness(trace, algorithm="ElasticTiresias", topology=topo,
                      rate_limit_seconds=rate, scale_out_hysteresis=hyst,
                      resize_cooldown_seconds=cooldown,
                      preemptions=config5_preemptions(topo)).run()
    return {
        "seed": seed, "rate": rate, "hyst": hyst, "cooldown": cooldown,
        "completed": r.completed, "failed": r.failed,
        "restarts": r.restarts_total,
        "ss_util": round(r.steady_state_utilization, 4),
        "att_util": round(r.attainable_utilization, 4),
        "avg_jct": round(r.avg_jct_seconds, 1),
        "p95_jct": round(r.p95_jct_seconds, 1),
        "makespan": round(r.makespan_seconds, 1),
        "ss_frac": round(r.steady_state_seconds / r.makespan_seconds, 3),
        "act_cp_s": r.actuation_critical_path_seconds,
        "act_sum_s": r.actuation_serial_sum_seconds,
    }


def knee() -> list:
    rows = []
    for rate, hyst, cd in itertools.product(RATES, HYSTERESIS, COOLDOWNS):
        row = run_one(PINNED_SEED, rate, hyst, cd)
        rows.append(row)
        print(f"rate={rate:4.0f} hyst={hyst:.1f} cd={cd:3.0f}  "
              f"util={row['ss_util']:.4f} avg={row['avg_jct']:7.1f} "
              f"p95={row['p95_jct']:8.1f} restarts={row['restarts']:4d} "
              f"ss_frac={row['ss_frac']:.3f} "
              f"{'INCOMPLETE' if row['completed'] != 64 else ''}",
              flush=True)
    return rows


def panel(rate: float, hyst: float, cooldown: float) -> list:
    rows = []
    for seed in PANEL_SEEDS:
        row = run_one(seed, rate, hyst, cooldown)
        rows.append(row)
        print(f"seed={seed:9d}  util={row['ss_util']:.4f} "
              f"avg={row['avg_jct']:7.1f} p95={row['p95_jct']:8.1f} "
              f"restarts={row['restarts']:4d} "
              f"{'INCOMPLETE' if row['completed'] != 64 else ''}",
              flush=True)
    return rows


# The shipped headline configuration (bench.py) — the panel's knobs when
# run standalone, and _best's fallback when no sweep cell qualifies.
# r7 pick: with resizes (not starts — a spawn never blocks its caller)
# priced at their critical path, the knee slows to a 20 s rate limit
# and hardens suppression (hysteresis 2.0, cooldown 300 s): a marginal
# grow now charges the pass its drain, so fewer are worth taking.
SHIPPED_KNEE = dict(rate=20.0, hyst=2.0, cooldown=300.0)


def _write(out: dict) -> None:
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "doc", "replay_sweep_r7.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print("wrote", path)


def main() -> None:
    mode = sys.argv[1] if len(sys.argv) > 1 else "all"
    out = {}
    if mode in ("knee", "all"):
        print("== knee sweep (pinned seed) ==")
        out["knee"] = knee()
        if mode == "all":
            _write(out)  # knee results survive even if the panel dies
    if mode in ("panel", "all"):
        knobs = _best(out["knee"]) if out.get("knee") else SHIPPED_KNEE
        print(f"== 8-seed panel at rate={knobs['rate']} "
              f"hyst={knobs['hyst']} cd={knobs['cooldown']} ==")
        out["panel"] = panel(knobs["rate"], knobs["hyst"], knobs["cooldown"])
        out["panel_knobs"] = knobs
    if mode == "all":
        _write(out)


def _best(rows: list) -> dict:
    """Knee pick: complete runs with an honest steady-state window,
    then lexicographic-ish score — utilization first (the north-star),
    avg JCT as tiebreak within 1% util."""
    ok = [r for r in rows if r["completed"] == 64 and r["ss_frac"] > 0.5]
    if not ok:
        ok = [r for r in rows if r["completed"] == 64]
    if not ok:
        print("WARNING: no sweep cell completed all jobs — panel falls "
              "back to the shipped knee")
        return dict(SHIPPED_KNEE)
    best_util = max(r["ss_util"] for r in ok)
    near = [r for r in ok if r["ss_util"] >= best_util - 0.01]
    # Within the util-equivalent set, balance mean against tail — on a
    # saturated workload the knobs move avg and p95 in opposite
    # directions, so neither alone picks a defensible knee. Exact ties
    # (whole knob ranges that never bound) break toward the shipped
    # values, so a flat axis doesn't flip a knob for no measured reason.
    def score(r):
        tie = sum(abs(r[k] - SHIPPED_KNEE[k2])
                  for k, k2 in (("rate", "rate"), ("hyst", "hyst"),
                                ("cooldown", "cooldown")))
        return (r["avg_jct"] + r["p95_jct"], tie)

    r = min(near, key=score)
    return dict(rate=r["rate"], hyst=r["hyst"], cooldown=r["cooldown"])


if __name__ == "__main__":
    main()
